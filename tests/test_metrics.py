"""Metrics aggregator + mock worker (reference components/metrics with
mock_worker.rs: the metrics plane is testable with no engine)."""

import asyncio

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.metrics import MetricsAggregator, MockWorker
from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.runtime import DistributedRuntime


def test_aggregator_scrapes_mock_workers(run_async):
    async def scenario():
        drt = await DistributedRuntime.detached()
        w1 = MockWorker(drt, component="mockw", seed=1,
                        hit_rate_interval=0.05)
        w2_drt = drt  # same process, same bus
        w2 = MockWorker(w2_drt, component="mockw", seed=2,
                        hit_rate_interval=0.05)
        await w1.start()
        await w2.start()

        agg = MetricsAggregator(drt, "dynamo", "mockw", interval=0.1)
        await agg.start()
        await asyncio.sleep(0.5)
        await agg.scrape_once()
        text = agg.render_prometheus()
        await agg.stop()
        await w1.stop()
        await w2.stop()
        await drt.shutdown()
        return agg, text

    agg, text = run_async(scenario())
    # both workers share a lease id? no — same drt => same worker id; the
    # stats plane keys by instance id, so one entry is expected here
    assert agg.worker_metrics, "no worker metrics scraped"
    assert "dyn_worker_cache_usage_perc" in text
    assert 'namespace="dynamo"' in text
    assert agg.hit_rate_events > 0
    assert "dyn_kv_hit_rate_overlap_blocks" in text
    assert "dyn_metrics_evicted_instances" in text


def test_scrape_target_eviction_under_churn(run_async):
    """Stale-endpoint hygiene: a worker that crashes WITHOUT deregistering
    (lease still alive, discovery record intact) is evicted from the
    scrape targets after consecutive probe failures instead of costing
    every round a failed probe forever — and a rejoin (fresh discovery
    put) restores it."""

    async def scenario():
        drt = await DistributedRuntime.detached()
        drt2 = await DistributedRuntime.attach(drt.dcp.address)
        w1 = MockWorker(drt, component="churn", seed=1,
                        hit_rate_interval=9e9,
                        profile=[ForwardPassMetrics(request_active_slots=1)])
        w2 = MockWorker(drt2, component="churn", seed=2,
                        hit_rate_interval=9e9,
                        profile=[ForwardPassMetrics(request_active_slots=2)])
        await w1.start()
        await w2.start()
        crash_id = drt2.instance_id

        agg = MetricsAggregator(drt, "dynamo", "churn")
        await agg.start(run_loop=False)
        await agg.scrape_once()
        healthy = dict(agg.worker_metrics)

        # crash w2: drop its request-plane subscriptions but leave the
        # discovery record (the keepalive thread still renews the lease)
        for sid in w2._handle._sids:
            await drt2.dcp.unsubscribe(sid)
        w2._handle._sids.clear()

        evictions_by_round = []
        for _ in range(Client.STATS_EVICTION_THRESHOLD):
            await agg.scrape_once()
            evictions_by_round.append(list(agg._client.evicted_ids()))
        metrics_after = dict(agg.worker_metrics)
        still_discovered = crash_id in agg._client.instances

        # rejoin: the worker re-registers (fresh discovery put) and must
        # immediately be a scrape target again
        await w2.stop()
        w3 = MockWorker(drt2, component="churn", seed=3,
                        hit_rate_interval=9e9,
                        profile=[ForwardPassMetrics(request_active_slots=3)])
        await w3.start()
        await asyncio.sleep(0.1)   # watch fanout
        rejoined_evicted = list(agg._client.evicted_ids())
        await agg.scrape_once()
        metrics_rejoined = dict(agg.worker_metrics)

        await agg.stop()
        await w1.stop()
        await w3.stop()
        await drt2.shutdown()
        await drt.shutdown()
        return (crash_id, healthy, evictions_by_round, metrics_after,
                still_discovered, rejoined_evicted, metrics_rejoined)

    (crash_id, healthy, rounds, after, still_discovered,
     rejoined_evicted, rejoined) = run_async(scenario())
    assert crash_id in healthy                       # scraped while alive
    assert rounds[-1] == [crash_id]                  # evicted at threshold
    assert all(not r for r in rounds[:-1])           # …not before
    assert crash_id not in after                     # metrics dropped too
    # discovery membership is NOT touched by the quarantine — the record
    # belongs to the (still-live) lease, not to this client
    assert still_discovered
    assert rejoined_evicted == []                    # put clears quarantine
    assert crash_id in rejoined                      # scraped again
    assert rejoined[crash_id].request_active_slots == 3
