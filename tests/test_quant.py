"""Weight-only int8 quantization (models/quant.py): exactness bounds of
the scheme, forward-parity tolerance vs bf16/f32 weights across model
families, loader/engine/TP plumbing. Reference analog: the reference's
flagship configs serve FP8 engines (docs/architecture.md:57-61); int8
weight-only is the TPU-native counterpart."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.quant import (QUANT_KEYS, QuantInt8, host_init_quantized,
                                     quantize_int8, quantize_int8_np,
                                     quantize_params)


def rel_l2(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return np.linalg.norm(a - b) / (np.linalg.norm(b) + 1e-12)


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    w = (rng.randn(3, 32, 16) * 0.07).astype(np.float32)
    for qw in (quantize_int8_np(w), quantize_int8(jnp.asarray(w))):
        err = np.abs(np.asarray(qw.dequant()) - w)
        # symmetric rounding: |w - q*s| <= s/2 elementwise
        assert (err <= np.asarray(qw.s) / 2 + 1e-7).all()
        assert np.asarray(qw.q).dtype == np.int8


def test_post_scale_matmul_matches_dequant():
    """x @ QuantInt8 computes (x @ q) * s — must equal dequant-then-
    matmul exactly in f32 (scale constant along the contraction)."""
    rng = np.random.RandomState(1)
    w = (rng.randn(24, 12) * 0.1).astype(np.float32)
    x = jnp.asarray(rng.randn(5, 24), jnp.float32)
    qw = quantize_int8(jnp.asarray(w))
    got = x @ qw
    want = x @ qw.dequant(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_getitem_and_scan_slice_consistency():
    w = (np.random.RandomState(2).randn(4, 8, 6) * 0.1).astype(np.float32)
    qw = quantize_int8_np(w)
    one = qw[1]
    np.testing.assert_allclose(np.asarray(one.dequant()),
                               np.asarray(qw.dequant())[1], rtol=1e-6)
    # jax.tree.map descends into the registered pytree (segment slicing
    # in models/mla.py relies on this)
    seg = jax.tree.map(lambda a: a[:2], QuantInt8(jnp.asarray(qw.q),
                                                  jnp.asarray(qw.s)))
    assert seg.q.shape[0] == 2 and seg.s.shape[0] == 2


def test_llama_forward_int8_close():
    from dynamo_tpu.models import llama

    cfg = ModelConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 500)
    ref = llama.reference_forward(params, cfg, tokens)
    qparams = quantize_params(params)
    assert isinstance(qparams["wq"], QuantInt8)
    got = llama.reference_forward(qparams, cfg, tokens)
    assert rel_l2(got, ref) < 0.05, rel_l2(got, ref)


def test_llama_moe_forward_int8_close():
    from dynamo_tpu.models import llama

    cfg = ModelConfig.tiny(num_experts=4, num_experts_per_tok=2,
                           model_type="mixtral")
    params = llama.init_params(cfg, jax.random.PRNGKey(3))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 10), 0, 500)
    ref = llama.reference_forward(params, cfg, tokens)
    qparams = quantize_params(params)
    assert isinstance(qparams["w_gate"], QuantInt8)  # [L, E, D, I]
    got = llama.reference_forward(qparams, cfg, tokens)
    # looser than the dense bound: with tiny random weights the router's
    # top-k flips for a few tokens under quantization noise, a
    # discontinuous (but bounded) contribution on top of the matmul error
    assert rel_l2(got, ref) < 0.25, rel_l2(got, ref)


def test_mla_forward_int8_close():
    from dynamo_tpu.models import mla

    cfg = ModelConfig.tiny(
        model_type="deepseek_v2", kv_lora_rank=16, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, q_lora_rank=24)
    params = mla.init_params(cfg, jax.random.PRNGKey(5))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 9), 0, 500)
    ref = mla.reference_forward(params, cfg, tokens)
    qparams = quantize_params(params)
    assert isinstance(qparams["w_uk"], QuantInt8)
    got = mla.reference_forward(qparams, cfg, tokens)
    assert rel_l2(got, ref) < 0.05, rel_l2(got, ref)


def test_paged_serving_int8_matches_reference_greedy():
    """The paged prefill+decode path with int8 weights greedy-decodes the
    same tokens as the int8 reference forward (quantization must commute
    with the serving machinery, not just the oracle)."""
    from dynamo_tpu.models import llama

    cfg = ModelConfig.tiny()
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)))
    spec = llama.KVCacheSpec(num_pages=16, page_size=8)
    kv_k, kv_v = llama.init_kv_cache(cfg, spec)
    prefill, decode = llama.make_step_fns(cfg)
    T = 11
    tokens = jax.random.randint(jax.random.PRNGKey(7), (1, T), 0, 500)
    ref = llama.reference_forward(params, cfg, tokens)

    positions = np.arange(T)[None, :]
    table = np.array([[0, 1, 0, 0]], np.int32)
    slots = (positions // 8) * 0  # page 0/1 layout below
    flat = np.where(positions < 8, positions, 8 + positions)  # page0 rows
    flat = np.array([[p if p < 8 else (1 * 8 + p - 8) for p in range(T)]],
                    np.int32)
    logits, kv_k, kv_v = prefill(
        params, tokens, jnp.asarray(positions), kv_k, kv_v,
        jnp.asarray(table), jnp.asarray(flat),
        jnp.full((1,), T - 1, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=2e-3, atol=2e-3)


def test_engine_int8_generates(run_async):
    """JaxEngine(quant='int8') end-to-end: host-init-quantized params,
    greedy generation completes, weights actually stored int8."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()
    eng = JaxEngine(cfg, EngineConfig(num_pages=32, page_size=8,
                                      max_batch=4),
                    quant="int8")
    assert isinstance(eng.params["wq"], QuantInt8)
    assert eng.params["wq"].q.dtype == jnp.int8

    async def go():
        req = PreprocessedRequest(
            token_ids=[1, 2, 3, 4],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=4, ignore_eos=True))
        out = []
        async for delta in eng.generate(req, Context()):
            out.extend(delta.token_ids or [])
        return out

    toks = run_async(go())
    assert len(toks) == 4


def test_loader_int8(tmp_path):
    """load_params(..., quant='int8') from a real HF checkpoint matches
    the f32 load within quantization tolerance."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.loader import load_params

    torch.manual_seed(11)
    hf_cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=128, tie_word_embeddings=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    path = tmp_path / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    cfg = ModelConfig.from_local_path(str(path))
    pf = load_params(str(path), cfg, dtype=jnp.float32)
    pq = load_params(str(path), cfg, dtype=jnp.float32, quant="int8")
    assert isinstance(pq["wo"], QuantInt8)
    tokens = jnp.asarray(np.arange(10)[None, :] % 120)
    ref = llama.reference_forward(pf, cfg, tokens)
    got = llama.reference_forward(pq, cfg, tokens)
    assert rel_l2(got, ref) < 0.05, rel_l2(got, ref)
    with pytest.raises(ValueError, match="quant"):
        load_params(str(path), cfg, quant="fp4")


def test_tp_sharded_int8_matches_single_device():
    """shard_params places QuantInt8 leaves (scale contraction axis kept
    unsharded); the sharded forward matches the unsharded one."""
    from jax.sharding import Mesh

    from dynamo_tpu.models import llama
    from dynamo_tpu.parallel.mesh import shard_params

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    cfg = ModelConfig.tiny()
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)))
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 8), 0, 500)
    ref = llama.reference_forward(params, cfg, tokens)

    devs = np.array(jax.devices()[:2]).reshape(1, 2, 1, 1)
    mesh = Mesh(devs, ("data", "model", "expert", "seq"))
    sp = shard_params(params, cfg, mesh)
    assert isinstance(sp["wo"], QuantInt8)
    got = llama.reference_forward(sp, cfg, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_host_init_quantized_device_placement():
    from dynamo_tpu.models import llama

    cfg = ModelConfig.tiny()
    p = host_init_quantized(llama, cfg, seed=0)
    assert isinstance(p["w_up"], QuantInt8)
    dev = jax.devices()[0]
    assert list(p["w_up"].q.devices()) == [dev]
    assert list(p["embed"].devices()) == [dev]


def test_synthetic_int8_params_serve(run_async):
    """The instant benchmark-only init (bench --model 8b path): correct
    tree structure, int8 quantized keys, finite outputs end-to-end
    through the engine."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.quant import synthetic_int8_params
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()
    params = synthetic_int8_params(llama, cfg)
    ref = set(llama.init_params(cfg, jax.random.PRNGKey(0)))
    assert set(params) == ref
    assert isinstance(params["wq"], QuantInt8)
    assert params["wq"].q.dtype == jnp.int8

    eng = JaxEngine(cfg, EngineConfig(num_pages=32, page_size=8,
                                      max_batch=4), params=params)

    async def go():
        req = PreprocessedRequest(
            token_ids=[1, 2, 3],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=3, ignore_eos=True))
        out = []
        async for d in eng.generate(req, Context()):
            out.extend(d.token_ids or [])
        await eng.stop()
        return out

    toks = run_async(go())
    assert len(toks) == 3 and all(0 <= t < cfg.vocab_size for t in toks)


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_engine_tp_int8_matches_single_device(run_async):
    """JaxEngine under a 4-device data x model mesh with int8 weights:
    generation completes and matches the single-device int8 engine
    token-for-token (QuantInt8 leaves survive shard_params, scan, and
    the TP decode path end-to-end)."""
    from jax.sharding import Mesh

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models import llama
    from dynamo_tpu.runtime.engine import Context

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    cfg = ModelConfig.tiny()
    params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)))
    ecfg = EngineConfig(page_size=8, num_pages=32, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(2, 4), page_buckets=(8,))
    devs = np.array(jax.devices()[:4]).reshape(2, 2, 1, 1, 1)
    mesh = Mesh(devs, ("data", "model", "expert", "seq", "stage"))

    async def gen(engine):
        req = PreprocessedRequest(
            token_ids=[3, 1, 4, 1, 5, 9, 2, 6],
            sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    single = JaxEngine(cfg, ecfg, params=params)
    want = run_async(gen(single))
    sharded = JaxEngine(cfg, ecfg, params=params, mesh=mesh)
    assert isinstance(sharded.params["wq"], QuantInt8)
    got = run_async(gen(sharded))
    assert len(want) == 6
    assert got == want


def test_ring_long_prefill_int8_close():
    """int8 weights through the sequence-parallel ring prefill — the
    quantized tree must survive shard_params + the ring layer scan."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.parallel.mesh import MeshSpec, shard_params
    from dynamo_tpu.parallel.ring_attention import make_long_prefill_fn

    cfg = ModelConfig.tiny()
    mesh = MeshSpec(seq=4, model=2).build()
    params = llama.init_params(cfg, jax.random.PRNGKey(1))
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(1, 500, (2, 32)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(32, dtype=jnp.int32), (2, 32))
    ref = llama.reference_forward(quantize_params(params), cfg, tokens)

    sq = shard_params(quantize_params(params), cfg, mesh)
    assert isinstance(sq["w_up"], QuantInt8)
    fn = make_long_prefill_fn(cfg, mesh)
    with jax.set_mesh(mesh):
        logits, _, _ = fn(sq, tokens, positions)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=5e-3, atol=5e-3)
