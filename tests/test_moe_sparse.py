"""Sparse MoE dispatch (models/llama.py moe_experts_blocked): parity
with the dense-over-experts einsum, the ~top_k/E FLOP claim (measured
via XLA cost analysis, not asserted by hand), quantized-weight
interplay, and serving-path engagement. Reference analog: vLLM's
fused_moe dispatch, which the reference's flagship Mixtral/DeepSeek
configs serve through."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models import llama


def _routing(key, N, E, k):
    logits = jax.random.normal(key, (N, E))
    w, idx = jax.lax.top_k(logits, k)
    return jax.nn.softmax(w, axis=-1), idx


def _dense_ref(x, w, idx, wg, wu, wd):
    E = wg.shape[0]
    gate = jnp.sum(jax.nn.one_hot(idx, E, dtype=jnp.float32)
                   * w[..., None], axis=-2)           # [N, E]
    ge = jnp.einsum("nd,edi->nei", x, wg)
    up = jnp.einsum("nd,edi->nei", x, wu)
    act = jax.nn.silu(ge) * up
    down = jnp.einsum("nei,eid->ned", act, wd)
    return jnp.einsum("ned,ne->nd", down, gate)


def _weights(key, E, D, I):
    k1, k2, k3 = jax.random.split(key, 3)
    s = 1.0 / np.sqrt(D)
    return (jax.random.normal(k1, (E, D, I)) * s,
            jax.random.normal(k2, (E, D, I)) * s,
            jax.random.normal(k3, (E, I, D)) / np.sqrt(I))


@pytest.mark.parametrize("N,E,k,block", [
    (512, 8, 2, 256),
    (300, 16, 4, 64),   # N*k not a block multiple; many experts
    (256, 4, 1, 256),   # k=1
])
def test_blocked_matches_dense(N, E, k, block):
    D, I = 32, 48
    wg, wu, wd = _weights(jax.random.PRNGKey(0), E, D, I)
    x = jax.random.normal(jax.random.PRNGKey(1), (N, D), jnp.float32)
    w, idx = _routing(jax.random.PRNGKey(2), N, E, k)
    ref = _dense_ref(x, w, idx, wg, wu, wd)
    got = llama.moe_experts_blocked(x, w, idx, wg, wu, wd, block=block)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_blocked_skewed_routing_no_drops():
    """Every token routed to ONE expert — the group padding must absorb
    the full N*k load on a single expert without dropping tokens (the
    correctness property capacity-based dispatches give up)."""
    N, E, k, D, I = 257, 8, 2, 16, 24
    wg, wu, wd = _weights(jax.random.PRNGKey(3), E, D, I)
    x = jax.random.normal(jax.random.PRNGKey(4), (N, D), jnp.float32)
    idx = jnp.full((N, k), 3, jnp.int32)
    w = jnp.full((N, k), 0.5, jnp.float32)
    ref = _dense_ref(x, w, idx, wg, wu, wd)
    got = llama.moe_experts_blocked(x, w, idx, wg, wu, wd, block=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_blocked_flops_scale_with_topk_not_experts():
    """XLA cost analysis: the blocked dispatch must cost ~top_k/E of the
    dense einsum. E=16, k=2 → exact ratio 1/8; padding and dispatch
    overhead allowed up to 1/3."""
    N, E, k, D, I = 1024, 16, 2, 64, 128
    wg, wu, wd = _weights(jax.random.PRNGKey(5), E, D, I)
    x = jax.random.normal(jax.random.PRNGKey(6), (N, D), jnp.float32)
    w, idx = _routing(jax.random.PRNGKey(7), N, E, k)

    def flops(fn):
        c = jax.jit(fn).lower(x, w, idx, wg, wu, wd).compile()
        return c.cost_analysis()["flops"]

    dense = flops(lambda *a: _dense_ref(*a))
    blocked = flops(lambda x, w, idx, wg, wu, wd:
                    llama.moe_experts_blocked(x, w, idx, wg, wu, wd,
                                              block=128))
    ratio = blocked / dense
    assert ratio < 1 / 3, f"blocked/dense flops = {ratio:.3f}"


def test_blocked_with_quantized_experts():
    """_dyn_expert slices the int8 stack THEN dequantizes — parity with
    quantize→dense within matmul tolerance."""
    from dynamo_tpu.models.quant import quantize_int8

    N, E, k, D, I = 300, 8, 2, 32, 48
    wg, wu, wd = _weights(jax.random.PRNGKey(8), E, D, I)
    x = jax.random.normal(jax.random.PRNGKey(9), (N, D), jnp.float32)
    w, idx = _routing(jax.random.PRNGKey(10), N, E, k)
    qg, qu, qd = quantize_int8(wg), quantize_int8(wu), quantize_int8(wd)
    ref = _dense_ref(x, w, idx, qg.dequant(jnp.float32),
                     qu.dequant(jnp.float32), qd.dequant(jnp.float32))
    got = llama.moe_experts_blocked(x, w, idx, qg, qu, qd, block=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cost_model_trigger():
    """The blocked path engages only where its worst-case row-MLP cost
    (N·k + E·block) is at most HALF the dense einsum's (N·E), and never
    under a >1-device mesh."""
    use = llama._moe_use_blocked
    # Mixtral-ish E=8, k=2, block=256: breakeven/2 at N=1024
    assert not use(None, 256, 8, 2, 256)   # blocked would be ~1.25x DENSE
    assert not use(None, 1023, 8, 2, 256)
    assert use(None, 1024, 8, 2, 256)
    # Qwen3-MoE-ish E=128, k=8: huge dense waste — engages much earlier
    assert use(None, 600, 128, 8, 256)
    assert not use(None, 128, 128, 8, 256)  # decode-sized: dense
    # never on a sharded mesh
    from dynamo_tpu.parallel.mesh import MeshSpec
    assert not use(MeshSpec(data=2, model=2, expert=2).build(), 4096, 8,
                   2, 256)


def test_moe_mlp_paths_agree(monkeypatch):
    """_moe_mlp with the blocked path engaged (small block via the env
    knob's module constant) == the dense path — strategy is a pure
    execution detail."""
    cfg = ModelConfig.tiny(num_experts=8, num_experts_per_tok=2,
                           model_type="mixtral")
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    wr, wg, wu, wd = (params[k][0] for k in
                      ("w_router", "w_gate", "w_up", "w_down"))
    big = jax.random.normal(jax.random.PRNGKey(1),
                            (1, 512, cfg.hidden_size), jnp.bfloat16)

    monkeypatch.setattr(llama, "_MOE_BLOCK", 64)  # N·k+E·64=1536 ≤ 2048
    assert llama._moe_use_blocked(None, 512, 8, 2, llama._MOE_BLOCK)
    out_blocked = llama._moe_mlp(big, wr, wg, wu, wd, 2)
    monkeypatch.setattr(llama, "_MOE_BLOCK", 1 << 30)  # forces dense
    out_dense = llama._moe_mlp(big, wr, wg, wu, wd, 2)
    np.testing.assert_allclose(
        np.asarray(out_blocked, np.float32),
        np.asarray(out_dense, np.float32),
        rtol=5e-2, atol=5e-2)  # bf16 inputs; different summation orders


def test_moe_serving_prefill_blocked_matches_dense(monkeypatch):
    """End-to-end through llama.forward (paged prefill): the blocked
    path engaged via a small block == the dense-forced forward."""
    cfg = ModelConfig.tiny(num_experts=8, num_experts_per_tok=2,
                           model_type="mixtral")
    params = llama.init_params(cfg, jax.random.PRNGKey(2))
    spec = llama.KVCacheSpec(num_pages=64, page_size=8)
    kv_k, kv_v = llama.init_kv_cache(cfg, spec)
    T = 256
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, T), 0, 500)
    positions = jnp.broadcast_to(jnp.arange(T), (1, T))
    table = jnp.arange(64, dtype=jnp.int32).reshape(1, 64)
    flat = table[0, positions // 8] * 8 + positions % 8

    def run():
        h, _, _ = llama.forward(params, cfg, tokens, positions, kv_k,
                                kv_v, table, flat)
        return h

    monkeypatch.setattr(llama, "_MOE_BLOCK", 32)  # 512+256 ≤ 2048/2
    assert llama._moe_use_blocked(None, T, 8, 2, llama._MOE_BLOCK)
    blocked_h = run()
    monkeypatch.setattr(llama, "_MOE_BLOCK", 1 << 30)
    dense_h = run()
    np.testing.assert_allclose(
        np.asarray(blocked_h, np.float32), np.asarray(dense_h, np.float32),
        rtol=5e-2, atol=5e-2)
