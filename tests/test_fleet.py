"""dynafleet: the deterministic fleet-scale serving simulator.

Tier-1 coverage:

- **smoke closed loop** — a small burst drives the real planner to emit a
  scale-up advisory, the fleet controller actually adds workers, and the
  post-scale SLO recovers (ROADMAP item 1's regression gate).
- **determinism** — the acceptance contract: ``--scenario burst --seed
  0`` twice renders byte-identical JSON reports.
- **crash churn** — a mid-stream worker crash fails fast, the stale
  endpoint is evicted from every collector's scrape targets, and the
  planner re-scales the pool.
- **traffic/model units** — seeded traces replay identically; the worker
  queueing model stamps deterministic lifecycle times.

Larger scenario sweeps are ``slow``-marked.
"""

import json

import pytest

from dynamo_tpu.fleet import (SCENARIOS, SimEngineModel, WorkerProfile,
                              burst, get_scenario, run_scenario)
from dynamo_tpu.fleet.clock import VirtualClock


# ----------------------------------------------------------- pure pieces


def test_traffic_trace_is_seed_deterministic():
    t1 = burst(3, steps=20, base_rate=1.5, burst_rate=6.0,
               burst_start=5, burst_end=10)
    t2 = burst(3, steps=20, base_rate=1.5, burst_rate=6.0,
               burst_start=5, burst_end=10)
    assert t1.requests == t2.requests
    assert [p.name for p in t1.phases] == ["warmup", "burst", "recovery"]
    t3 = burst(4, steps=20, base_rate=1.5, burst_rate=6.0,
               burst_start=5, burst_end=10)
    assert t1.requests != t3.requests  # different seed, different trace


def test_sim_engine_model_lifecycle():
    clock = VirtualClock()
    seen = []
    model = SimEngineModel(
        "w0", WorkerProfile(slots=1, prefill_steps=2, tokens_per_step=4),
        block_size=8, clock=clock.now,
        on_lifecycle=lambda rid, ev, vt: seen.append((rid, ev, vt)))
    r1 = model.submit("a", list(range(16)), max_tokens=8)
    r2 = model.submit("b", list(range(16)), max_tokens=4)
    # step 0: a admitted (slot 1 of 1), prefill 1/2; b waits
    model.step()
    assert ("a", "admitted", 0.0) in seen
    assert model.stats()["num_requests_waiting"] == 1
    clock.advance()
    model.step()   # a: prefill done -> first 4 tokens
    assert ("a", "first_token", 1.0) in seen
    clock.advance()
    model.step()   # a: last 4 tokens -> done; b still waiting
    assert ("a", "done", 2.0) in seen
    clock.advance()
    model.step()   # b admitted
    assert ("b", "admitted", 3.0) in seen
    assert r1.finished and not r2.finished
    # events queues carry the released batches
    assert r1.events.qsize() == 2


# ------------------------------------------------------------ smoke loop


def test_smoke_scenario_closes_the_loop(run_async):
    """Burst -> planner advisory -> controller adds workers -> SLO
    recovers. The tier-1 closed-loop regression gate."""
    report = run_async(run_scenario(get_scenario("smoke"), seed=0))

    # the planner emitted at least one scale-up advisory under the burst
    ups = [a for a in report["advisories"] if a["direction"] == "up"]
    assert ups, f"no scale-up advisory: {report['advisories']}"
    assert ups[0]["at"] >= 6.0  # during the burst window, virtual time

    # the fleet controller actually added workers
    scale_ups = [a for a in report["actuations"]
                 if a["action"] == "scale-up" and a["workers"]]
    assert scale_ups, f"advisory never actuated: {report['actuations']}"
    assert report["workers"]["peak_live"] > 2  # grew past the initial 2

    # the loop also closed through the k8s dry-run reconcile controller
    assert report["k8s_dry_run"]["deployment_replicas"] == \
        ups[-1]["desired_replicas"]

    # post-scale recovery: queue drained after the burst and the final
    # phase met the scenario SLO
    assert report["slo"]["time_to_recover_s"] is not None
    assert report["slo"]["met"], report["phases"]
    assert report["phases"]["recovery"]["queue_wait_p95_s"] \
        <= report["slo"]["targets"]["queue_wait_p95_s"]
    assert report["phases"]["recovery"]["ttft_p95_s"] \
        <= report["slo"]["targets"]["ttft_p95_s"]

    # every request made it through the real HTTP/router path
    assert report["requests"]["failed"] == 0
    assert report["requests"]["completed"] == report["requests"]["total"]
    # advisory timeline is recorded in virtual time
    assert all(isinstance(a["at"], float) for a in report["advisories"])


def test_burst_reports_identical_across_runs(run_async):
    """The acceptance contract: same scenario + seed => byte-identical
    report, across independent event loops."""
    sc = get_scenario("burst")
    r1 = run_async(run_scenario(sc, seed=0))
    r2 = run_async(run_scenario(get_scenario("burst"), seed=0))
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)
    # different seed produces a different trace (sanity that the seed
    # actually flows through)
    assert r1["requests"]["total"] > 0


def test_crash_scenario_evicts_and_rescales(run_async):
    """Worker crash mid-stream: the stale endpoint is quarantined off
    every collector's scrape targets and the planner re-scales the pool
    — and since dynarevive, the in-flight streams on the crashed worker
    RESUME on siblings instead of failing (mid-stream failover)."""
    report = run_async(run_scenario(get_scenario("crash"), seed=0))

    crashes = [e for e in report["workers"]["timeline"]
               if e["event"] == "crash"]
    assert len(crashes) == 1
    # dynarevive: the crashed worker's in-flight streams resumed on a
    # sibling — the crash is no longer client-visible (pre-revive this
    # asserted failed >= 1; the failure mode is now a resume)
    assert report["requests"]["failed"] == 0
    assert report["requests"]["resumed"] >= 1
    assert report["failover"]["still_crashed"] == 0
    # stale-endpoint hygiene: both collectors evicted the crashed
    # instance from their scrape targets
    assert report["stats_evictions"]["aggregator"]
    assert report["stats_evictions"]["router"]
    # the planner saw the shrunken pool and re-scaled it
    ups = [a for a in report["actuations"] if a["action"] == "scale-up"]
    assert ups and ups[0]["vt"] > crashes[0]["vt"]
    assert report["slo"]["met"], report["phases"]


# ------------------------------------------------------------ slow sweep


@pytest.mark.slow
@pytest.mark.parametrize("name", ["diurnal", "hot-tenant", "blackout",
                                  "join", "pd_rebalance"])
def test_scenario_sweep(run_async, name):
    report = run_async(run_scenario(get_scenario(name), seed=1))
    assert report["requests"]["completed"] > 0
    assert report["slo"]["met"], report["phases"]
    if name == "hot-tenant":
        # shared-prefix traffic must register overlap in BOTH views:
        # the router's predicted overlap AND the workers' realized
        # (engine-side) stored-chain replay (dynacache)
        assert report["router"]["avg_hit_rate"] > 0.3
        assert report["cache"]["router_predicted_hit_rate"] > 0.3
        assert report["cache"]["engine_realized_hit_rate"] > 0.3
    if name == "blackout":
        # zero-observed advisories are published but never actuated
        ignored = [a for a in report["actuations"]
                   if a["action"] == "ignored-zero-observed"]
        zero_obs = [a for a in report["advisories"]
                    if a["current_replicas"] == 0]
        assert len(ignored) == len(zero_obs) > 0
        assert report["workers"]["peak_live"] == 3


def test_scenario_registry_complete():
    for name in SCENARIOS:
        sc = get_scenario(name)
        assert sc.steps > 0 and sc.initial_workers >= 1
        trace = sc.traffic(0)
        assert trace.total > 0
        assert trace.requests == sc.traffic(0).requests  # replayable
    with pytest.raises(ValueError):
        get_scenario("nope")
