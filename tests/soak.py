"""Soak/stress drive (reference lib/runtime/tests/soak.rs + python
bindings soak.py): hammer the distributed serving path in one process for
N seconds and report throughput + failure counts. Not collected by
pytest's default run — invoke directly:

    python tests/soak.py [--seconds 30] [--concurrency 32]
"""

import argparse
import asyncio
import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


async def main(seconds: float, concurrency: int) -> int:
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.detached()

    async def handler(request, context):
        for i in range(int(request["n"])):
            yield {"i": i, "payload": request["payload"]}

    comp = drt.namespace("soak").component("svc")
    await comp.create_service()
    handle = await comp.endpoint("generate").serve(handler)
    client = await comp.endpoint("generate").client()
    await client.wait_for_instances()

    stop_at = time.monotonic() + seconds
    stats = {"requests": 0, "items": 0, "errors": 0}

    async def worker(wid: int):
        rng = random.Random(wid)
        while time.monotonic() < stop_at:
            n = rng.randint(1, 16)
            payload = "x" * rng.randint(1, 4096)
            try:
                stream = await client.round_robin({"n": n,
                                                   "payload": payload})
                got = 0
                async for env in stream:
                    assert env.data["payload"] == payload
                    got += 1
                assert got == n, f"expected {n} items, got {got}"
                stats["requests"] += 1
                stats["items"] += got
            except Exception as e:  # noqa: BLE001
                stats["errors"] += 1
                print(f"worker {wid}: {e!r}", file=sys.stderr)

    t0 = time.monotonic()
    await asyncio.gather(*(worker(i) for i in range(concurrency)))
    wall = time.monotonic() - t0
    await client.close()
    await handle.stop()
    await drt.shutdown()
    print(f"soak: {stats['requests']} requests, {stats['items']} items, "
          f"{stats['errors']} errors in {wall:.1f}s "
          f"({stats['requests']/wall:.0f} req/s)")
    return 1 if stats["errors"] else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=10)
    ap.add_argument("--concurrency", type=int, default=32)
    args = ap.parse_args()
    sys.exit(asyncio.run(main(args.seconds, args.concurrency)))
