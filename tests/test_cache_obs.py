"""dynacache: end-to-end KV/prefix-cache observability (ISSUE 11).

Covers the four planes the tentpole wires together:

- PageManager lifecycle telemetry: allocation prefix split (device hit /
  host restore / fresh) with conservation, eviction fates + block age,
  restore-queue depth and drain latency, bounded hot-prefix tracking;
- engine surfaces: windowed vs lifetime hit rate, the per-request cost
  block's prefix split (conservation like PR 10's dispatch-share test),
  host-restored attribution, /debug/cache;
- the stats()→ForwardPassMetrics→Prometheus SYNC GATE: every numeric
  stats key either rides a rendered gauge or sits on an explicit
  skip-list (the drift class PR 10 found by hand, made impossible);
- the REAL stack: a shared-prefix workload through aiohttp → HttpService
  → Processor → KvRouter → token worker → JaxEngine reports
  prefix_hit_rate > 0 with router-predicted vs engine-realized
  attribution and zero post-warmup compiles.
"""

import asyncio
import os
import sys
import types
from collections import deque

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dynamo_tpu.engine.kv_manager import (PageManager,  # noqa: E402
                                          chain_hashes)


# ------------------------------------------------- PageManager telemetry


def test_alloc_split_counters_and_conservation():
    pm = PageManager(num_pages=32, page_size=4)
    prompt = list(range(17))  # 5 blocks (4 full + tail)
    a = pm.allocate_sequence(prompt)
    assert (a.device_hit_blocks, a.host_restored_blocks) == (0, 0)
    assert a.fresh_blocks == len(a.pages) == 5
    # commit the full blocks, release, re-allocate the same prompt
    for i, h in enumerate(chain_hashes(prompt[:16], 4)):
        pm.commit(a.pages[i], h)
    pm.release_sequence(a.pages)
    b = pm.allocate_sequence(prompt)
    assert b.device_hit_blocks == 4 and b.host_restored_blocks == 0
    # conservation: split sums to the allocated page count, and the
    # cumulative counters add up the same way
    assert (b.device_hit_blocks + b.host_restored_blocks
            + b.fresh_blocks) == len(b.pages)
    assert pm.device_hit_blocks_total == 4
    assert pm.fresh_blocks_total == 5 + 1  # first alloc + b's tail block
    # hot-prefix tracking saw the 4 reused hashes
    top = pm.top_prefixes(10)
    assert len(top) == 4 and all(t["hits"] == 1 for t in top)
    assert all(t["tier"] == "device" for t in top)


def test_eviction_fate_split_and_age():
    # no host tier: every committed eviction is a drop
    pm = PageManager(num_pages=6, page_size=2)
    a = pm.allocate_sequence([1, 2, 3, 4])  # 2 pages
    for i, h in enumerate(chain_hashes([1, 2, 3, 4], 2)):
        pm.commit(a.pages[i], h)
    pm.release_sequence(a.pages)
    # pool has 5 usable pages; claim them all so reusable pages evict
    claimed = [pm.allocate_page() for _ in range(5)]
    assert all(p is not None for p in claimed)
    assert pm.evict_dropped_total == 2
    assert pm.evict_offloaded_total == 0
    assert pm.evict_age_seconds_total >= 0.0

    # host tier: the same churn offloads instead
    pm2 = PageManager(num_pages=6, page_size=2, host_pages=8)
    b = pm2.allocate_sequence([1, 2, 3, 4])
    for i, h in enumerate(chain_hashes([1, 2, 3, 4], 2)):
        pm2.commit(b.pages[i], h)
    pm2.release_sequence(b.pages)
    for _ in range(5):
        pm2.allocate_page()
    assert pm2.evict_offloaded_total == 2
    assert pm2.evict_dropped_total == 0
    assert pm2.cache_stats()["evict_offloaded_total"] == 2


def test_restore_queue_depth_and_drain_wait():
    pm = PageManager(num_pages=6, page_size=2, host_pages=8)
    prompt = [1, 2, 3, 4, 5]
    a = pm.allocate_sequence(prompt)
    for i, h in enumerate(chain_hashes(prompt[:4], 2)):
        pm.commit(a.pages[i], h)
    pm.release_sequence(a.pages)
    for _ in range(5):  # evict both committed blocks into the host tier
        pm.allocate_page()
    assert pm.evict_offloaded_total == 2
    pm.drain_tier_ops()  # flush the offload copies; no restores yet
    assert pm.restores_drained_total == 0
    # free the pool again and re-allocate: host hits queue restores
    for p in range(1, pm.num_pages):
        if pm.pages[p].refcount:
            pm.release_sequence([p])
    b = pm.allocate_sequence(prompt)
    assert b.host_restored_blocks == 2
    assert pm.cache_stats()["restore_queue_depth"] == 2
    _, res = pm.drain_tier_ops()
    assert len(res) == 2
    st = pm.cache_stats()
    assert st["restore_queue_depth"] == 0
    assert st["restores_drained_total"] == 2
    assert st["restore_wait_seconds_total"] >= 0.0
    assert pm._restore_enq == {}  # stamps consumed


def test_hot_prefix_tracking_is_bounded():
    pm = PageManager(num_pages=8, page_size=2)
    pm._hit_track_cap = 3
    for h in range(10):
        if h in pm._hit_counts:
            pm._hit_counts[h] += 1
        elif len(pm._hit_counts) < pm._hit_track_cap:
            pm._hit_counts[h] = 1
    assert len(pm._hit_counts) == 3
    assert len(pm.top_prefixes(2)) == 2


# ------------------------------------------------------- engine surfaces


def _tiny_engine(host_pages=0, num_pages=64, seed=0):
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=num_pages, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,),
                        host_pages=host_pages, watermark_pages=2)
    return JaxEngine(cfg, ecfg, seed=seed)


async def _gen(engine, prompt, n=6, rid=None):
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt), sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
        eos_token_ids=[])
    ctx = Context(rid) if rid else Context()
    cost = None
    async for out in engine.generate(req, ctx):
        if out.finish_reason:
            cost = out.cost
            break
    return cost


def test_windowed_hit_rate_tracks_recent_traffic():
    """The windowed rate forgets old traffic; the lifetime ratio cannot
    (the ISSUE 11 satellite: the aggregator gauge must reflect recent
    traffic)."""
    from dynamo_tpu.engine.jax_engine import JaxEngine

    eng = object.__new__(JaxEngine)  # windowed math only — no device
    eng._hit_window = deque(maxlen=4)
    for _ in range(4):
        eng._hit_window.append((8, 8))  # 100% hits
    assert JaxEngine._windowed_hit_rate(eng) == 1.0
    for _ in range(4):
        eng._hit_window.append((0, 8))  # recent traffic: all misses
    assert JaxEngine._windowed_hit_rate(eng) == 0.0
    assert JaxEngine._windowed_hit_rate(
        types.SimpleNamespace(_hit_window=deque())) == 0.0


def test_cost_block_prefix_split_conservation(run_async):
    """device_hit + host_restored + fresh == prompt blocks on every cost
    block (the dynacache analog of PR 10's dispatch-share conservation),
    with host_restored > 0 after an evict→restore round trip."""

    async def scenario():
        engine = _tiny_engine(host_pages=32, num_pages=16)
        rng = np.random.RandomState(0)
        prompt_a = rng.randint(1, 500, 20).tolist()  # 5 blocks
        c1 = await _gen(engine, prompt_a)
        # churn the tiny pool so A's blocks spill to the host tier
        for _ in range(4):
            await _gen(engine, rng.randint(1, 500, 20).tolist())
        c2 = await _gen(engine, prompt_a)
        snap = engine.cache_snapshot()
        await engine.stop()
        return c1, c2, snap

    c1, c2, snap = run_async(scenario())
    for cost in (c1, c2):
        assert cost is not None
        assert (cost["device_hit_blocks"] + cost["host_restored_blocks"]
                <= cost["prompt_blocks"])
        fresh = (cost["prompt_blocks"] - cost["device_hit_blocks"]
                 - cost["host_restored_blocks"])
        assert fresh >= 0
    assert c1["device_hit_blocks"] == 0 and c1["host_restored_blocks"] == 0
    assert c2["host_restored_blocks"] > 0, \
        "evicted prompt should restore from the host tier"
    assert c2["restore_wait_ms"] >= 0.0
    # snapshot mirrors the counters and carries the hot chains
    assert snap["host_restored_blocks_total"] >= c2["host_restored_blocks"]
    assert snap["restores_drained_total"] > 0
    assert snap["pool"]["total_blocks"] == 15
    assert isinstance(snap["top_prefixes"], list)


# ----------------------------------------------- stats→Prometheus sync gate


def test_stats_prometheus_sync_gate(run_async):
    """Every numeric engine stats() key must either be a
    ForwardPassMetrics field that the aggregator RENDERS, or sit on the
    explicit STATS_PROMETHEUS_SKIP list. Sentinel-value rendering makes
    silent drift (a counter that stops at the stats plane) impossible."""
    from dynamo_tpu.llm.kv_router.protocols import (
        STATS_PROMETHEUS_SKIP, ForwardPassMetrics)
    from dynamo_tpu.metrics.component import MetricsAggregator

    engine = _tiny_engine()

    async def scenario():
        await _gen(engine, list(range(1, 9)))
        st = engine.stats()
        await engine.stop()
        return st

    st = run_async(scenario())
    fpm_fields = set(ForwardPassMetrics.__dataclass_fields__)
    numeric = {k for k, v in st.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    unrouted = numeric - fpm_fields - set(STATS_PROMETHEUS_SKIP)
    assert not unrouted, (
        f"engine stats() keys {sorted(unrouted)} reach neither a "
        f"ForwardPassMetrics field nor STATS_PROMETHEUS_SKIP — add a "
        f"gauge or an explicit skip entry")
    # skip-list hygiene: every entry is a REAL stats key with a reason
    for k, why in STATS_PROMETHEUS_SKIP.items():
        assert k in st and why

    # sentinel render: every numeric FPM field must appear in the
    # aggregator's exposition text
    sentinels = {}
    fpm = ForwardPassMetrics()
    for i, name in enumerate(sorted(fpm_fields)):
        if isinstance(getattr(fpm, name), (dict, str)):
            # dicts render as labeled families; strings are identity
            # LABELS (worker_label/mesh_shape — dynashard), not counters
            continue
        val = 900000 + i if isinstance(getattr(fpm, name), int) \
            else round(0.5 + i / 1000.0, 3)
        setattr(fpm, name, val)
        sentinels[name] = val
    agg = MetricsAggregator.__new__(MetricsAggregator)
    agg.namespace = "gate"
    agg.worker_metrics = {7: fpm}
    agg.hit_rate_isl_blocks = agg.hit_rate_overlap_blocks = 0
    agg.hit_rate_events = 0
    agg.scrape_failures_total = agg.consecutive_scrape_failures = 0
    agg._client = None
    text = agg.render_prometheus()
    missing = [name for name, val in sentinels.items()
               if f" {val}" not in text]
    assert not missing, (
        f"ForwardPassMetrics fields {missing} are never rendered by the "
        f"metrics aggregator — every stats-plane field must reach a "
        f"Prometheus gauge")


# -------------------------------------------------- /debug/cache endpoint


def test_debug_cache_endpoint(run_async):
    """GET /debug/cache renders every registered cache view — the tiny
    engine registered itself at construction."""

    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.service import HttpService

        engine = _tiny_engine()
        await _gen(engine, list(range(1, 9)))
        service = HttpService()
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{service.port}/debug/cache"
                ) as resp:
                    assert resp.status == 200
                    body = await resp.json()
        finally:
            await service.stop()
            await engine.stop()
        return body

    body = run_async(main())
    engines = [v for k, v in body["caches"].items()
               if k.startswith("jax-engine-")]
    assert engines, body["caches"].keys()
    snap = engines[-1]
    assert {"pool", "host_tier", "hit_rate_windowed", "top_prefixes",
            "restore_queue_depth"} <= set(snap)


# ------------------------------------------- the REAL stack, shared-prefix


def _shared_args(**over):
    base = dict(
        sweep=None, scenario="shared", shared_shape="multi_tenant",
        isl=96, osl=8, requests=8, concurrency=4, model="tiny",
        dtype="bf16", users=3, turns=3, host_pages=0,
        disagg_threshold=256, seed=0, decode_steps=2,
        prefill_token_budget=None, host_tier_int8=False, max_batch=None,
        spec=False, cpu=True, prof_sample=0, trace=False,
        shared_prefix=False)
    base.update(over)
    return types.SimpleNamespace(**base)


def test_shared_prefix_bench_e2e_through_real_stack():
    """The acceptance scenario: a shared-prefix workload through
    HTTP→Processor→KvRouter→JaxEngine reports prefix_hit_rate > 0 with
    the router-predicted vs engine-realized attribution breakdown, cost
    blocks conserve the prefix split, the TTFT A/B is present, and no
    post-warmup compile fired."""
    import bench

    report = asyncio.run(bench.run_shared(_shared_args()))
    assert report["post_warmup_compiles"] == 0
    assert report["prefix_hit_rate"] > 0
    shape = report["shapes"]["multi_tenant"]
    share, noshare = shape["share"], shape["noshare"]
    assert share["errors"] == 0 and noshare["errors"] == 0
    # no-sharing control cannot hit; the shared leg must
    assert noshare["prefix_hit_rate"] == 0.0
    assert share["prefix_hit_rate"] > 0
    assert share["device_hit_blocks"] > 0
    # router calibration: predictions were compared against realized
    # splits, and overlap routing onto one worker should be exact here
    calib = report["calibration"]
    assert calib["compared"] > 0
    assert calib["predicted_blocks_total"] > 0
    assert calib["realized_blocks_total"] > 0
    # cost-block conservation over the whole leg (router-predicted vs
    # engine-realized vs host-restored breakdown present)
    for leg in (share, noshare):
        cs = leg["cost_split"]
        assert cs["requests_with_cost"] == leg["requests"]
        assert (cs["device_hit_blocks"] + cs["host_restored_blocks"]
                + cs["fresh_blocks"]) == cs["prompt_blocks"]
    assert share["cost_split"]["router_overlap_blocks"] > 0
    assert "ttft_delta_ms" in shape


def test_disagg_shared_prefix_ab_smoke():
    """--shared-prefix disagg leg: same engines, shared-prefix prompts —
    the transfer-vs-reuse A/B reports transfer pages per remote prefill
    for both legs plus the decode engine's realized hit split."""
    import bench

    args = _shared_args(scenario="disagg", isl=96, osl=4, requests=3,
                        concurrency=2, disagg_threshold=16,
                        kv_chunk_pages="2", shared_prefix=True)
    report = asyncio.run(bench.run_disagg(args))
    ab = report["shared_prefix_ab"]
    assert ab["fresh"]["remote_prefills"] > 0
    # the shared leg reuses decode-side blocks...
    assert ab["shared"]["decode_hit_blocks"] > 0
    # ...and therefore ships fewer total pages over the wire for the
    # same request count (per-remote ratios can even rise: big hits
    # shrink the remaining prefill below the disagg threshold and route
    # LOCAL — also reuse at work, so totals are the honest comparison)
    assert ab["shared"]["transfer_pages"] < ab["fresh"]["transfer_pages"]
