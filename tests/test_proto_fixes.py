"""dynaproto true-positive regression tests (ISSUE 15).

The declared-protocol passes (DL019/DL020 + the model checker over
``runtime/proto.py``) surfaced real ordering/handling bugs in the
drain/revive glue; per the PR 8 fix-not-baseline policy each fix lands
with a regression test here:

1. ``ServeHandle.begin_drain`` flipped the nack flag BEFORE awaiting the
   discovery delete — the model-checked `delete-before-nack` invariant
   of the `serve_handle.drain` machine. A client nacked in that window
   would re-pick the same still-discoverable instance until its retry
   budget died. The delete now completes first.
2. ``begin_drain`` is claim-before-await idempotent: two concurrent
   drains must not double-withdraw the record.
3. ``ServeHandle._run_request``'s error-frame delivery swallowed EVERY
   exception (``except Exception: pass``) — now only connection-level
   failures are absorbed, so a real bug in the error path is
   crash-logged instead of vanishing.
4. Runtime conformance: with ``DYN_PROTO_VALIDATE=1`` every transition
   the real ``CircuitBreaker`` takes is validated against the declared
   `breaker` machine — the full closed/open/half-open/probe/reset cycle
   raises nothing, and an undeclared transition raises typed.
"""

import asyncio

import pytest

from dynamo_tpu.runtime import proto
from dynamo_tpu.runtime.guard import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                      BREAKER_OPEN, BreakerConfig,
                                      CircuitBreaker)


@pytest.fixture(autouse=True)
def _no_proto_validation(monkeypatch):
    monkeypatch.delenv("DYN_PROTO_VALIDATE", raising=False)


# ------------------------------------------------- drain ordering (fix 1)


def test_begin_drain_deletes_discovery_before_nacks_enabled(run_async):
    """The discovery delete must complete while the nack flag is still
    OFF (delete-before-nack): a request arriving mid-drain either still
    gets served or is routed to a sibling — never nacked while routers
    can still pick this instance."""

    async def main():
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                yield {"ok": True}

            ep = drt.namespace("order").component("w").endpoint("gen")
            handle = await ep.serve(handler)

            seen = []
            real_delete = drt.dcp.kv_delete

            async def spying_delete(key):
                # the state the nack path reads, at delete time
                seen.append(handle.draining)
                await asyncio.sleep(0.01)   # widen the window
                seen.append(handle.draining)
                return await real_delete(key)

            drt.dcp.kv_delete = spying_delete
            await handle.begin_drain()
            assert seen == [False, False], (
                "nacks were enabled before the discovery delete "
                "completed (delete-before-nack invariant)")
            assert handle.draining is True
            await handle.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_begin_drain_concurrent_single_withdraw(run_async):
    """Two racing begin_drain calls withdraw the record exactly once
    (claim-before-await idempotency)."""

    async def main():
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                yield {"ok": True}

            ep = drt.namespace("order2").component("w").endpoint("gen")
            handle = await ep.serve(handler)

            calls = []
            real_delete = drt.dcp.kv_delete

            async def counting_delete(key):
                calls.append(key)
                await asyncio.sleep(0.01)
                return await real_delete(key)

            drt.dcp.kv_delete = counting_delete
            await asyncio.gather(handle.begin_drain(),
                                 handle.begin_drain())
            assert len(calls) == 1
            assert handle.draining is True
            await handle.stop()
        finally:
            await drt.shutdown()

    run_async(main())


# ------------------------------------- error-frame delivery (fix 3)


class _StubCallHome:
    """TcpCallHome double: records frames; error() can be rigged to
    fail like a dead connection."""

    def __init__(self, error_exc=None):
        self.sent = []
        self.errors = []
        self.closed = False
        self._error_exc = error_exc

    async def send_data(self, payload):
        self.sent.append(payload)

    async def complete(self):
        pass

    async def error(self, message, kind=None):
        if self._error_exc is not None:
            raise self._error_exc
        self.errors.append((message, kind))

    async def close(self):
        self.closed = True


def test_error_frame_conn_failure_absorbed_and_inflight_popped(
        run_async, monkeypatch):
    """A dead call-home conn while delivering the error frame must not
    leak the request from the inflight table (the caller already sees
    the drop); only connection-level failures are absorbed."""

    async def main():
        from dynamo_tpu.runtime import component as comp
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                raise ValueError("handler exploded")
                yield  # pragma: no cover — makes this an async gen

            ep = drt.namespace("err").component("w").endpoint("gen")
            handle = await ep.serve(handler)

            stub = _StubCallHome(error_exc=ConnectionError("conn gone"))

            class _Stub:
                @staticmethod
                async def connect(conn_info, on_ctrl):
                    return stub

            monkeypatch.setattr(comp, "TcpCallHome", _Stub)
            await handle._run_request("rid-1", object(), {"x": 1})
            assert "rid-1" not in handle._inflight
            assert stub.closed
            await handle.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_error_frame_carries_typed_kind(run_async, monkeypatch):
    """The handler's exception class name crosses the wire as the err
    frame `kind` — the mechanism AsyncResponseStream uses to re-raise
    DeadlineExceeded/NoCapacity typed on the caller (the justification
    for _run_request's DL021 suppression)."""

    async def main():
        from dynamo_tpu.runtime import component as comp
        from dynamo_tpu.runtime.guard import NoCapacity
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                raise NoCapacity("full up")
                yield  # pragma: no cover

            ep = drt.namespace("err2").component("w").endpoint("gen")
            handle = await ep.serve(handler)
            stub = _StubCallHome()

            class _Stub:
                @staticmethod
                async def connect(conn_info, on_ctrl):
                    return stub

            monkeypatch.setattr(comp, "TcpCallHome", _Stub)
            await handle._run_request("rid-2", object(), {"x": 1})
            assert stub.errors and stub.errors[0][1] == "NoCapacity"
            await handle.stop()
        finally:
            await drt.shutdown()

    run_async(main())


# --------------------------------------- runtime conformance (fix 4)


def test_breaker_full_cycle_conforms_to_declared_machine(monkeypatch):
    """DYN_PROTO_VALIDATE=1: every transition the real breaker takes is
    checked against the `breaker` machine; the full lifecycle raises
    nothing."""
    monkeypatch.setenv("DYN_PROTO_VALIDATE", "1")
    br = CircuitBreaker(BreakerConfig(threshold=2, probe_every=2))
    assert br.allow() and br.state == BREAKER_CLOSED
    br.record_failure()
    br.record_failure()                    # trip
    assert br.state == BREAKER_OPEN
    assert not br.allow()                  # deny 1
    assert br.allow()                      # deny 2 -> probe granted
    assert br.state == BREAKER_HALF_OPEN
    assert not br.allow()                  # single probe: second denied
    br.release_probe()                     # slot returned
    assert br.allow()                      # re-granted
    br.record_failure()                    # probe failed -> open
    assert br.state == BREAKER_OPEN
    assert br.allow() is False or True     # denial counting
    br.reset()                             # external reset -> closed
    assert br.state == BREAKER_CLOSED
    br.record_success()                    # success in closed
    assert br.state == BREAKER_CLOSED


def test_step_rejects_undeclared_transition(monkeypatch):
    monkeypatch.setenv("DYN_PROTO_VALIDATE", "1")
    with pytest.raises(proto.ProtocolError, match="not declared"):
        proto.step("breaker", "closed", "half_open")
    with pytest.raises(proto.ProtocolError, match="unknown state"):
        proto.step("breaker", "closed", "molten")
    with pytest.raises(proto.ProtocolError, match="unknown protocol"):
        proto.step("no-such-machine", "a", "b")
    # off by default: the same undeclared transition is a no-op
    monkeypatch.setenv("DYN_PROTO_VALIDATE", "0")
    proto.step("breaker", "closed", "half_open")


def test_journal_close_exactly_once():
    """The close edges all leave `open`, so a second close is a no-op —
    the model-checked close-exactly-once contract."""
    from dynamo_tpu.runtime import revive

    ring = revive.ReviveJournal(capacity=4, max_tokens=16)
    ring.open("r1", prompt_tokens=3)
    assert len(ring) == 1
    ring.close("r1")
    assert len(ring) == 0
    ring.close("r1")   # second close: idempotent, never a KeyError
    assert len(ring) == 0
