"""dynaheat: cost-aware eviction, batched/overlapped restores, int8
host-tier default, and router-overlap autotune.

Eviction policy is A/B'd at the PageManager level (`lru` is the
pre-dynaheat control, `cost` the GreedyDual hot-prefix policy); the
restore-overlap pipeline is pinned by engine-level token identity against
the serial drain; cost_diff's cache counter family closes the evidence
loop for --scenario shared A/Bs.
"""

import numpy as np
import pytest

from dynamo_tpu.engine.kv_manager import PageManager, chain_hashes


def _commit_all(pm, pages, prompt):
    hashes = chain_hashes(prompt, pm.page_size)
    for i, h in enumerate(hashes):
        pm.commit(pages[i], h, parent_hash=hashes[i - 1] if i else None)


def _heat(pm, hot, rounds):
    """Re-allocate ``hot`` (+ a partial tail so BOTH full blocks are
    matchable — the tail cap would otherwise shield the last block from
    ever being hit) to build up its hit counts."""
    for _ in range(rounds):
        a = pm.allocate_sequence(hot + [900, 901, 902])
        assert a is not None
        pm.release_sequence(a.pages)


def _churn(pm, n, base=5000):
    """n distinct single-block prompts, committed + released, so each one
    consumes a free page (or evicts a reusable one) and then parks in the
    reusable pool itself."""
    for i in range(n):
        prompt = [base + 4 * i + j for j in range(4)]
        a = pm.allocate_sequence(prompt)
        assert a is not None
        _commit_all(pm, a.pages, prompt)
        pm.release_sequence(a.pages)


@pytest.mark.parametrize("policy,survives", [("cost", True), ("lru", False)])
def test_hot_prefix_vs_cold_churn(policy, survives):
    """The policy split dynaheat exists for: a hot 2-block prefix (12
    reuses) against a stream of one-shot cold blocks. LRU evicts the hot
    blocks first (they were freed before the churn), GreedyDual keeps
    them (priority = clock + 1 + hits, and the clock only advances ~1
    per cold eviction — a 12-hit block outlives 12 cold evictions)."""
    pm = PageManager(num_pages=10, page_size=4, evict_policy=policy)
    hot = list(range(8))  # 2 full blocks
    a = pm.allocate_sequence(hot)
    _commit_all(pm, a.pages, hot)
    pm.release_sequence(a.pages)
    _heat(pm, hot, rounds=12)
    hot_hashes = chain_hashes(hot, 4)
    assert all(h in pm.by_hash for h in hot_hashes)
    # 9 usable pages, 2 hold the hot blocks: 10 cold blocks = 7 via the
    # free list + 3 evictions
    _churn(pm, 10)
    resident = [h for h in hot_hashes if h in pm.by_hash]
    if survives:
        assert resident == hot_hashes, "cost policy must keep the hot prefix"
        b = pm.allocate_sequence(hot + [903])
        assert b.cached_tokens == 8 and b.device_hit_blocks == 2
        pm.release_sequence(b.pages)
    else:
        assert resident == [], "lru control must have evicted the hot prefix"


def test_cost_policy_hot_block_ages_out():
    """GreedyDual aging: once-hot blocks must not squat forever. After
    enough cold evictions push the clock past the hot priority, the hot
    blocks go too (no immortal entries)."""
    pm = PageManager(num_pages=10, page_size=4, evict_policy="cost")
    hot = list(range(8))
    a = pm.allocate_sequence(hot)
    _commit_all(pm, a.pages, hot)
    pm.release_sequence(a.pages)
    _heat(pm, hot, rounds=4)  # priority ~ clock + 5
    # ~43 evictions over 7 circulating cold pages pushes the clock past
    # the hot priority (clock climbs ~1 per cold generation)
    _churn(pm, 50)
    hot_hashes = chain_hashes(hot, 4)
    assert not any(h in pm.by_hash for h in hot_hashes)


def test_conservation_and_evict_fates():
    """Invariants the counters must keep under mixed traffic: every
    allocation's prefix split sums to its page count, HBM evictions of
    committed blocks split exactly into offloaded + dropped, and no slot
    pin survives a full drain."""
    pm = PageManager(num_pages=4, page_size=4, host_pages=2,
                     evict_policy="cost")  # 3 usable HBM, 2 host slots
    prompt = list(range(12))  # 3 blocks
    a = pm.allocate_sequence(prompt)
    assert (a.device_hit_blocks + a.host_restored_blocks
            + a.fresh_blocks) == len(a.pages)
    _commit_all(pm, a.pages, prompt)
    pm.release_sequence(a.pages)

    # 3 committed blocks evicted into a 2-slot host tier: two get slots,
    # the third finds both slots pinned by the queued offloads → dropped.
    # Fates partition the evictions exactly.
    b = pm.allocate_sequence(list(range(100, 112)))
    assert (b.device_hit_blocks + b.host_restored_blocks
            + b.fresh_blocks) == len(b.pages)
    off, res = pm.drain_tier_ops()
    assert pm.evict_offloaded_total + pm.evict_dropped_total == 3
    assert pm.evict_offloaded_total == len(off) == 2
    _commit_all(pm, b.pages, list(range(100, 112)))
    pm.release_sequence(b.pages)

    # host hit → restore: split counts it as host_restored
    c = pm.allocate_sequence(prompt)
    assert c.host_restored_blocks == len(c.restores) > 0
    assert (c.device_hit_blocks + c.host_restored_blocks
            + c.fresh_blocks) == len(c.pages)
    off, res = pm.drain_tier_ops()
    assert pm.restore_batches_total == 1
    assert pm.restore_batch_pages_total == len(res)
    # totals mirror the per-alloc splits
    st = pm.cache_stats()
    allocs = (a, b, c)
    assert st["device_hit_blocks_total"] == sum(x.device_hit_blocks
                                                for x in allocs)
    assert st["host_restored_blocks_total"] == sum(x.host_restored_blocks
                                                   for x in allocs)
    assert st["fresh_blocks_total"] == sum(x.fresh_blocks for x in allocs)
    assert st["evict_policy"] == "cost"
    assert pm._slot_pins == {}, "pins must drain to zero with the queues"


@pytest.mark.parametrize("policy", ["lru", "cost"])
def test_fully_pinned_host_tier_drops(policy):
    """When every host slot is pinned by queued restores, a new eviction
    must take the drop path (removed event + evict_dropped) — never
    reassign an in-flight slot — and the pins must still drain to
    zero."""
    pm = PageManager(num_pages=4, page_size=4, host_pages=2,
                     evict_policy=policy)  # 3 usable, 2 host slots
    p1 = list(range(8))  # 2 blocks
    a = pm.allocate_sequence(p1)
    _commit_all(pm, a.pages, p1)
    pm.release_sequence(a.pages)
    b = pm.allocate_sequence(list(range(100, 112)))  # evicts both to host
    pm.drain_tier_ops()
    _commit_all(pm, b.pages, list(range(100, 112)))
    pm.release_sequence(b.pages)
    pm.drain_events()

    dropped0 = pm.evict_dropped_total
    # p1 + a tail token so BOTH blocks clear the last-block reuse cap:
    # queues 2 restores (pinning both slots), and the same call's 3
    # fresh-page pops evict b's committed blocks into the fully-pinned
    # host tier → dropped, with removed events
    c = pm.allocate_sequence(p1 + [77])
    assert len(c.restores) == 2
    assert sum(pm._slot_pins.values()) >= 2
    assert pm.evict_dropped_total > dropped0
    assert [e for e in pm.drain_events() if e.kind == "removed"]
    pm.drain_tier_ops()
    assert pm._slot_pins == {}


def test_host_eviction_accounting():
    """A full, unpinned host tier evicts ITS policy victim to admit a new
    offload — counted host_evictions (the HBM eviction itself is still
    offloaded), with a removed event once the block leaves both tiers."""
    pm = PageManager(num_pages=2, page_size=2, host_pages=1)  # 1 usable
    a = pm.allocate_sequence([0, 1])
    _commit_all(pm, a.pages, [0, 1])
    pm.release_sequence(a.pages)
    b = pm.allocate_sequence([10, 11])   # evicts A → offload to slot 0
    off, _ = pm.drain_tier_ops()         # unpins slot 0
    assert len(off) == 1
    _commit_all(pm, b.pages, [10, 11])
    pm.release_sequence(b.pages)
    pm.drain_events()
    c = pm.allocate_sequence([20, 21])   # evicts B → host full → evict A
    assert c is not None
    assert pm.host_evictions_total == 1
    assert pm.evict_offloaded_total == 2
    assert pm.evict_dropped_total == 0
    assert [e for e in pm.drain_events() if e.kind == "removed"]


def test_evict_policy_validation():
    with pytest.raises(ValueError):
        PageManager(num_pages=4, page_size=4, evict_policy="mru")


def test_host_tier_int8_default_resolution(monkeypatch):
    """dynaheat flips int8 page moves DEFAULT-ON whenever a host tier
    exists; DYN_HOST_TIER_FP16=1 is the lossless fallback; an explicit
    EngineConfig value always wins."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny()

    def make(**kw):
        ecfg = EngineConfig(page_size=4, num_pages=8, max_batch=2,
                            prefill_chunk=16, prefill_buckets=(16,),
                            batch_buckets=(2,), page_buckets=(8,), **kw)
        return JaxEngine(cfg, ecfg, seed=0)

    monkeypatch.delenv("DYN_HOST_TIER_FP16", raising=False)
    assert make(host_pages=16).ecfg.host_tier_int8 is True
    assert make(host_pages=0).ecfg.host_tier_int8 is False
    monkeypatch.setenv("DYN_HOST_TIER_FP16", "1")
    assert make(host_pages=16).ecfg.host_tier_int8 is False
    assert make(host_pages=16,
                host_tier_int8=True).ecfg.host_tier_int8 is True


def _engine_restore_cycle(run_async, overlap):
    """One engine run of the churn-out-then-restore workload; returns
    (first, again, restore_pages_total)."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=24, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,),
                        host_pages=64, watermark_pages=2,
                        host_tier_int8=False,  # identity: lossless tier
                        restore_overlap=overlap)
    engine = JaxEngine(cfg, ecfg, seed=0)

    async def gen(prompt, n=8):
        req = PreprocessedRequest(
            token_ids=prompt, sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        return toks

    async def scenario():
        rng = np.random.RandomState(7)
        prompt_a = rng.randint(1, 500, 24).tolist()  # 6 pages
        first = await gen(prompt_a)
        for _ in range(4):  # churn A out of the 23-page HBM pool
            await gen(rng.randint(1, 500, 24).tolist())
        again = await gen(prompt_a)
        await engine.stop()
        return first, again, engine.restore_pages_total

    return run_async(scenario())


def test_restore_overlap_token_identity(run_async):
    """Overlapped drain (stage at drain N, inject at drain N+1) must
    reproduce the original continuation exactly — the staged rows carry
    the same content the serial path injects, and prefill on the pages
    stays gated until injection."""
    first, again, restored = _engine_restore_cycle(run_async, overlap=True)
    assert len(first) == 8
    assert first == again
    assert restored > 0, "workload must actually exercise restores"


@pytest.mark.slow
def test_restore_overlap_matches_serial(run_async):
    """A/B: the overlapped pipeline and the serial drain produce
    token-identical output and restore the same page count."""
    f_o, a_o, r_o = _engine_restore_cycle(run_async, overlap=True)
    f_s, a_s, r_s = _engine_restore_cycle(run_async, overlap=False)
    assert f_o == a_o == f_s == a_s
    assert r_o == r_s > 0


def test_router_autotune_moves_weight():
    """Over-prediction (index promises overlap the engines don't hold)
    must shift load_balance_weight toward load; perfect calibration must
    not move it; the weight stays clamped and is exported as a gauge."""
    from dynamo_tpu.llm.kv_router.scheduler import KvScheduler
    from dynamo_tpu.runtime import guard

    s = KvScheduler(block_size=4, autotune=True, autotune_gain=0.5,
                    autotune_window=4)
    w0 = s.load_balance_weight
    for _ in range(4):  # predicted 8, realized 2 of 8 → bias 0.75
        s.observe_calibration(predicted=8, realized=2, isl_blocks=8)
    assert s.load_balance_weight > w0
    assert s.autotune_adjustments == 1
    assert abs(guard.counter_value("dyn_kv_router_load_balance_weight")
               - s.load_balance_weight) < 1e-9

    # zero bias: window fills, weight holds
    w1 = s.load_balance_weight
    for _ in range(4):
        s.observe_calibration(predicted=4, realized=4, isl_blocks=8)
    assert s.load_balance_weight == w1

    # clamp: huge sustained bias cannot push past alpha_max
    for _ in range(40):
        s.observe_calibration(predicted=8, realized=0, isl_blocks=8)
    assert s.alpha_min <= s.load_balance_weight <= s.alpha_max

    # toggle off: a disabled scheduler never moves
    s2 = KvScheduler(block_size=4, autotune=False)
    for _ in range(128):
        s2.observe_calibration(predicted=8, realized=0, isl_blocks=8)
    assert s2.load_balance_weight == 0.3
    assert s2.autotune_adjustments == 0


def test_cost_diff_cache_family(tmp_path, capsys):
    """The cache counter family rides cost_diff: two --scenario shared
    reports (flat dynaheat keys, NO bucket cost table) diff cleanly with
    before/after/delta per key and a rendered cache section."""
    import json

    from tools import cost_diff

    def rep(hit, p95, wait, off_, drop):
        return {"metric": "m", "value": hit, "unit": "rate", "detail": {
            "prefix_hit_rate": hit, "hit_rate_windowed": hit,
            "ttft_p95_ms": p95, "restore_wait_ms": wait,
            "restore_batch_pages_mean": 2.0,
            "device_hit_blocks": 10, "host_restored_blocks": 5,
            "fresh_blocks": 20, "evict_offloaded_total": off_,
            "evict_dropped_total": drop, "host_evictions_total": 1,
            "post_warmup_compiles": 0}}

    before = rep(0.30, 80.0, 40.0, 3, 9)
    after = rep(0.45, 60.0, 25.0, 10, 2)
    diff = cost_diff.diff_reports(before, after)
    assert round(diff["cache"]["prefix_hit_rate"]["delta"], 4) == 0.15
    assert diff["cache"]["restore_wait_ms"]["delta"] == -15.0
    assert diff["cache"]["evict_dropped_total"]["delta"] == -7
    assert diff["headline"]["ttft_p95_ms"]["delta"] == -20.0

    bf, af = tmp_path / "b.json", tmp_path / "a.json"
    bf.write_text(json.dumps(before))
    af.write_text(json.dumps(after))
    # cache-only reports (no bucket table) are NOT an error
    assert cost_diff.main([str(bf), str(af)]) == 0
    out = capsys.readouterr().out
    assert "cache (dynaheat)" in out
    assert "prefix_hit_rate" in out
