"""Native C++ components (native/*.cpp): radix index equivalence vs the
Python tree, and the C ABI KV-event shim round-trip (reference
lib/bindings/c + kv_router/indexer.rs)."""

import ctypes
import random

import pytest

from dynamo_tpu.llm.kv_router.indexer import RadixTree
from dynamo_tpu.llm.kv_router.protocols import KvCacheEventWire
from dynamo_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _stored(worker, hashes, parent=None):
    return KvCacheEventWire(worker_id=worker, kind="stored",
                            block_hashes=list(hashes), parent_hash=parent)


def _removed(worker, hashes):
    return KvCacheEventWire(worker_id=worker, kind="removed",
                            block_hashes=list(hashes))


def make_cpp():
    from dynamo_tpu.llm.kv_router.native_indexer import CppRadixTree

    return CppRadixTree()


def test_cpp_basic_match():
    t = make_cpp()
    t.apply_event(_stored(1, [10, 11, 12]))
    t.apply_event(_stored(2, [10, 11]))
    s = t.find_matches([10, 11, 12, 13])
    assert s.scores == {1: 3, 2: 2}
    assert t.block_count() == 3
    t.apply_event(_removed(2, [11]))
    assert t.find_matches([10, 11, 12]).scores == {1: 3, 2: 1}
    t.remove_worker(1)
    assert t.find_matches([10, 11, 12]).scores == {2: 1}


def test_cpp_parent_anchor():
    t = make_cpp()
    t.apply_event(_stored(7, [1, 2]))
    # continuation anchored at parent hash 2
    t.apply_event(_stored(7, [3, 4], parent=2))
    assert t.find_matches([1, 2, 3, 4]).scores == {7: 4}


def test_cpp_matches_python_randomized():
    """Property test: C++ and Python trees agree on every query under a
    random event stream (stored/removed/remove_worker)."""
    rng = random.Random(42)
    py, cpp = RadixTree(), make_cpp()
    # worker → list of chains it stored (for realistic removals)
    chains = []
    for step in range(300):
        op = rng.random()
        if op < 0.55 or not chains:
            w = rng.randint(1, 5)
            base = rng.randint(0, 6)
            length = rng.randint(1, 6)
            hashes = [(base + i) * 1000 + rng.randint(0, 2)
                      for i in range(length)]
            parent = hashes[0] - 1000 if rng.random() < 0.4 else None
            ev = _stored(w, hashes, parent)
            chains.append((w, hashes))
        elif op < 0.85:
            w, hashes = rng.choice(chains)
            k = rng.randint(1, len(hashes))
            ev = _removed(w, rng.sample(hashes, k))
        else:
            w = rng.randint(1, 5)
            py.remove_worker(w)
            cpp.remove_worker(w)
            continue
        py.apply_event(ev)
        cpp.apply_event(ev)
        # random queries
        for _ in range(3):
            q = [rng.randint(0, 8) * 1000 + rng.randint(0, 2)
                 for _ in range(rng.randint(1, 8))]
            assert cpp.find_matches(q).scores == py.find_matches(q).scores, \
                f"divergence at step {step} on query {q}"
    assert cpp.block_count() == py.block_count()


def test_event_shim_roundtrip():
    lib = native.load()
    assert lib.dynamo_llm_init(b"ns", b"comp", 77, 64) == 0
    parent = ctypes.c_uint64(123)
    blocks = (ctypes.c_uint64 * 2)(111, 222)
    assert lib.dynamo_kv_event_publish_stored(
        1, None, None, blocks, 2, ctypes.byref(parent), 0) == 0
    blocks2 = (ctypes.c_uint64 * 1)(111)
    assert lib.dynamo_kv_event_publish_removed(2, blocks2, 1) == 0

    from dynamo_tpu.llm.kv_router.publisher import NativeEventBridge

    class FakeDcp:
        async def publish(self, subject, payload):
            pass

    bridge = NativeEventBridge(FakeDcp(), "ns", "comp", worker_id=77)
    events = bridge.drain()
    assert [e.kind for e in events] == ["stored", "removed"]
    assert events[0].block_hashes == [111, 222]
    assert events[0].parent_hash == 123
    assert events[1].block_hashes == [111]
    assert events[1].parent_hash is None
    assert bridge.drain() == []  # buffer empties
    lib.dynamo_llm_shutdown()


def test_event_shim_high_water_drops_oldest():
    """An undrained shim must not grow without bound (ADVICE r1): above
    the 4 MiB high-water mark the oldest whole events are discarded and
    counted, and the newest survive."""
    lib = native.load()
    lib.dynamo_kv_events_dropped.restype = ctypes.c_uint64
    assert lib.dynamo_llm_init(b"ns", b"comp", 5, 64) == 0
    base_dropped = lib.dynamo_kv_events_dropped()
    n_blocks = 1024                       # ~8 KiB per event
    blocks = (ctypes.c_uint64 * n_blocks)(*range(n_blocks))
    n_events = 700                        # ~5.7 MiB total > 4 MiB cap
    for eid in range(n_events):
        assert lib.dynamo_kv_event_publish_stored(
            eid, None, None, blocks, n_blocks, None, 0) == 0
    dropped = lib.dynamo_kv_events_dropped() - base_dropped
    assert dropped > 0
    # drain everything that's left: newest event must have survived
    from dynamo_tpu.llm.kv_router.publisher import NativeEventBridge

    class FakeDcp:
        async def publish(self, subject, payload):
            pass

    bridge = NativeEventBridge(FakeDcp(), "ns", "comp", worker_id=5)
    events = []
    while True:
        batch = bridge.drain()
        if not batch:
            break
        events.extend(batch)
    assert len(events) == n_events - dropped
    assert events[-1].block_hashes == list(range(n_blocks))
    # total retained stays at/under the high-water mark (~8KiB records)
    assert len(events) * n_blocks * 8 <= 4 * 1024 * 1024 + 8192 * 2
    lib.dynamo_llm_shutdown()


def test_kv_indexer_uses_native_backend():
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer
    from dynamo_tpu.llm.kv_router.native_indexer import CppRadixTree

    ix = KvIndexer(block_size=4)
    assert isinstance(ix.tree, CppRadixTree)
    ix_py = KvIndexer(block_size=4, backend="python")
    assert isinstance(ix_py.tree, RadixTree)
    # same end-to-end scores through the tokens façade
    from dynamo_tpu.engine.kv_manager import chain_hashes

    tokens = list(range(16))
    hashes = chain_hashes(tokens, 4)
    for t in (ix, ix_py):
        t.apply_event(_stored(3, hashes))
    assert ix.find_matches_for_request(tokens).scores == \
        ix_py.find_matches_for_request(tokens).scores == {3: 4}
