"""dynaprof: loop-lag monitor, stall watchdog, sampled device/host split,
per-request cost attribution, /debug/profile round-trip.

The central invariants:

- ``DYN_PROF_SAMPLE=0`` (default) adds ZERO host syncs to the serving hot
  path: the compile fence stays at 0, the profiler records nothing, and
  the step timeline carries no profiler events (byte-identical event
  stream to a build without dynaprof).
- A sampled run produces a non-empty per-bucket cost table and a
  device/host split without breaking the zero-compile invariant.
- Attribution conserves dispatches: every dispatch distributes exactly
  1.0 of step share across its batch, so the per-request shares sum to
  the engine's dispatch counter.
"""

import asyncio
import json
import time

import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                             SamplingOptions,
                                             StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime import Context, profiling, tracing


@pytest.fixture
def run_async():
    def run(coro):
        return asyncio.run(coro)

    return run


# ------------------------------------------------------- loop lag monitor


def test_loop_lag_monitor_records_stall(run_async):
    """An injected blocking callback shows up as sleep-drift ≥ its
    duration in the monitor's percentiles."""

    async def main():
        mon = profiling.LoopLagMonitor(interval_s=0.01)
        mon.start()
        await asyncio.sleep(0.05)          # a few clean samples
        time.sleep(0.15)                   # the stalled callback
        await asyncio.sleep(0.05)          # let the late wakeup land
        snap = mon.snapshot()
        await mon.stop()
        return snap

    snap = run_async(main())
    assert snap["samples"] >= 2
    assert snap["max_s"] >= 0.1
    assert snap["p99_s"] >= 0.1
    assert snap["p50_s"] < snap["max_s"] + 1e-9


def _deliberate_stall(duration: float) -> None:
    time.sleep(duration)


def test_stall_watchdog_captures_folded_stack(run_async):
    """While a loop callback overruns the threshold, the watchdog samples
    the loop thread's stack; the stalling frame appears in the
    flamegraph-ready collapsed output."""

    async def main():
        mon = profiling.LoopLagMonitor(interval_s=0.01)
        dog = profiling.StallWatchdog(mon, threshold_s=0.05, poll_s=0.02)
        mon.start()
        dog.start()
        await asyncio.sleep(0.05)          # heartbeat established
        _deliberate_stall(0.4)             # watchdog fires during this
        dog.stop()
        folded = dog.folded()
        snap = dog.snapshot()
        await mon.stop()
        return folded, snap

    folded, snap = run_async(main())
    assert snap["captures"] >= 1
    assert "_deliberate_stall" in folded
    # collapsed-stack format: "frame;frame;... count" lines
    line = folded.splitlines()[0]
    stack, count = line.rsplit(" ", 1)
    assert ";" in stack and int(count) >= 1


def test_fold_stack_format():
    import sys

    folded = profiling.fold_stack(sys._getframe())
    assert folded.endswith("test_profiling.test_fold_stack_format")


def test_watchdog_bounded_stacks(run_async):
    """Past max_stacks, new distinct stacks are counted as dropped, not
    accumulated (the ring is bounded)."""

    async def main():
        mon = profiling.LoopLagMonitor(interval_s=0.01)
        mon.start()
        await asyncio.sleep(0.02)
        dog = profiling.StallWatchdog(mon, threshold_s=10.0, max_stacks=1)
        dog.capture()                       # first shape: kept

        def other_frame():
            return dog.capture()            # second shape: dropped

        other_frame()
        snap = dog.snapshot()
        await mon.stop()
        return snap

    snap = run_async(main())
    assert snap["captures"] == 2
    assert snap["distinct_stacks"] == 1
    assert snap["dropped"] == 1


# ------------------------------------------------- engine sampled profiling


def _req(tokens, mt=6, **sampling):
    return PreprocessedRequest(
        token_ids=list(tokens), sampling=SamplingOptions(**sampling),
        stop=StopConditions(max_tokens=mt, ignore_eos=True),
        eos_token_ids=[])


def _tiny_engine(**overrides) -> JaxEngine:
    cfg = ModelConfig.tiny()
    kw = dict(page_size=8, num_pages=64, max_batch=4, prefill_chunk=32,
              batch_buckets=(1, 2, 4), prefill_buckets=(16, 32),
              page_buckets=(8,), max_prefill_batch=2, decode_steps=2)
    kw.update(overrides)
    eng = JaxEngine(cfg, EngineConfig(**kw), seed=0)
    eng.warmup()
    return eng


async def _drive(eng, reqs):
    """Run requests to completion; returns (token lists, finish cost
    blocks)."""
    costs = []

    async def one(r):
        toks = []
        async for out in eng.generate(r, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason is not None:
                assert out.finish_reason != "error"
                costs.append(out.cost)
        return toks

    results = await asyncio.gather(*(one(r) for r in reqs))
    return results, costs


def test_sampled_device_host_split(run_async):
    """DYN_PROF_SAMPLE=1 (every step): the cost table fills per compiled
    program, the device/host split is measured, and the sampled syncs
    trigger no post-warmup compile."""
    eng = _tiny_engine(prof_sample=1)

    async def main():
        out = await _drive(eng, [_req(list(range(1, 20))),
                                 _req([7] * 24, mt=5),
                                 _req(list(range(40, 45)), mt=4)])
        await eng.stop()
        return out

    run_async(main())
    prof = eng.profiler
    assert prof.profiled_steps > 0
    assert 0.0 < prof.device_time_fraction() <= 1.0
    table = prof.cost_table()
    assert table, "sampled run must produce a per-bucket cost table"
    assert any(k.startswith("prefill:") for k in table)
    assert any(k.startswith(("decode_window:", "decode:")) for k in table)
    for row in table.values():
        assert row["samples"] >= 1
        assert row["device_us"] >= 0.0
    # the sampled sync is a drain, not a new program: fence stays 0
    assert eng.fence.post_warmup_compiles == 0
    st = eng.stats()
    assert st["bucket_cost"] == table
    assert st["device_time_fraction"] == round(
        prof.device_time_fraction(), 4)
    assert st["profiled_steps_total"] == prof.profiled_steps
    # sampled dispatches landed in the step timeline
    kinds = [e["kind"] for e in eng.step_timeline.snapshot()]
    assert "prof_sample" in kinds
    # loop-lag gauges ride stats() (engine.start acquired the monitor)
    assert st["loop_lag_p99_seconds"] >= 0.0
    eng.fence.disarm()


def test_sample_zero_adds_no_syncs(run_async):
    """The default-off contract: with DYN_PROF_SAMPLE=0 the mixed
    prefill/decode e2e shows post_warmup_compiles == 0, the profiler
    records NOTHING, and the step timeline carries no profiler events —
    the same event stream as a build without dynaprof."""
    eng = _tiny_engine()            # prof_sample=None -> env default 0
    assert eng.profiler.sample == 0

    async def main():
        out = await _drive(eng, [_req(list(range(1, 20))),
                                 _req([9] * 24, mt=6),
                                 _req(list(range(50, 55)), mt=4,
                                      temperature=0.9, seed=7)])
        await eng.stop()
        return out

    (results, costs) = run_async(main())
    assert all(len(r) >= 4 for r in results)
    assert eng.fence.post_warmup_compiles == 0
    assert eng.profiler.profiled_steps == 0
    assert eng.profiler.device_seconds_total == 0.0
    assert eng.profiler.cost_table() == {}
    kinds = {e["kind"] for e in eng.step_timeline.snapshot()}
    assert "prof_sample" not in kinds
    assert kinds <= {"admit", "prefill", "decode", "decode_window",
                     "spec_verify", "compile"}
    # attribution is ALWAYS on (host counters only): every finish chunk
    # carries a cost block even with sampling off
    assert len(costs) == 3 and all(c is not None for c in costs)
    assert all(c["device_ms_est"] is None for c in costs)  # nothing sampled
    eng.fence.disarm()


def test_attribution_sums_to_engine_totals(run_async):
    """Conservation: each dispatch distributes exactly 1.0 step share
    over its batch, so per-request shares sum to the engine's dispatch
    counter; per-request token counts sum to the engine totals."""
    eng = _tiny_engine(prof_sample=2)
    reqs = [_req(list(range(1, 20)), mt=6),
            _req([3] * 24, mt=5),
            _req(list(range(60, 70)), mt=4),
            _req(list(range(80, 85)), mt=3)]

    async def main():
        out = await _drive(eng, reqs)
        await eng.stop()
        return out

    _results, costs = run_async(main())
    assert len(costs) == len(reqs)
    share_sum = sum(c["device_step_share"] for c in costs)
    assert share_sum == pytest.approx(eng.batch_dispatches_total,
                                      rel=1e-4)
    # per-request generated counts include the first token (sampled by
    # the prefill dispatch); the engine's decode counter starts after it
    assert sum(c["decode_tokens"] for c in costs) == \
        eng.decode_tokens_total + len(reqs)
    assert sum(c["prompt_tokens"] for c in costs) == \
        eng.prompt_tokens_total
    for c in costs:
        assert c["queue_wait_ms"] >= 0.0
        assert c["kv_pages_peak"] >= 1
        assert c["kv_bytes_peak"] > 0
        assert c["dispatches"] >= 1
    # sampled run: the share-scaled device estimate is populated
    assert any(c["device_ms_est"] is not None for c in costs)
    # the engine also registered every attribution in the process ring
    assert profiling.request_attribution is not None
    eng.fence.disarm()


# ------------------------------------------------ stats -> ForwardPassMetrics


def test_engine_gauges_reach_forward_pass_metrics(run_async):
    """The dynaprof + engine-internal stats() keys map onto
    ForwardPassMetrics fields (that name match is what carries them to
    the aggregator's dyn_engine_* gauges)."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    eng = _tiny_engine(prof_sample=1)

    async def main():
        await _drive(eng, [_req(list(range(1, 12)), mt=4)])
        await eng.stop()

    run_async(main())
    m = ForwardPassMetrics.from_dict(eng.stats())
    assert m.kv_free_blocks > 0
    assert m.batch_dispatches_total >= 2
    assert m.queue_wait_seconds_total >= 0.0
    assert m.device_time_fraction > 0.0
    assert m.bucket_cost
    # aggregator render path: the labeled bucket-cost family appears
    from dynamo_tpu.metrics.component import MetricsAggregator

    agg = MetricsAggregator.__new__(MetricsAggregator)
    agg.namespace = "t"
    agg.worker_metrics = {1: m}
    agg.hit_rate_isl_blocks = agg.hit_rate_overlap_blocks = 0
    agg.hit_rate_events = 0
    agg.scrape_failures_total = agg.consecutive_scrape_failures = 0
    agg._client = None
    text = agg.render_prometheus()
    assert "dyn_engine_device_time_fraction" in text
    assert "dyn_engine_bucket_cost_us{" in text
    assert 'quantile="p99"' in text
    assert "dyn_engine_kv_free_blocks" in text
    eng.fence.disarm()


# -------------------------------------------------------- timeline anchors


def test_step_timeline_anchor_alignment():
    """Rings constructed at different times export alignable wall
    ``ts_ms``: two events recorded at (nearly) the same instant land
    within tolerance of each other despite different ring anchors."""
    tl1 = tracing.StepTimeline(8)
    time.sleep(0.05)
    tl2 = tracing.StepTimeline(8)
    tl1.add("x")
    tl2.add("x")
    e1 = tl1.snapshot()[0]
    e2 = tl2.snapshot()[0]
    # raw monotonic offsets differ by the construction gap...
    assert e1["mono_ms"] - e2["mono_ms"] > 25
    # ...but the anchor-aligned wall stamps agree
    assert abs(e1["ts_ms"] - e2["ts_ms"]) < 25
    a = tl1.anchors()
    assert set(a) == {"anchor_wall_ms", "anchor_monotonic_ms"}


# ------------------------------------------------- HTTP /debug + /v1/traces


def test_debug_profile_round_trip(run_async):
    """/debug/profile snapshot, collapsed-stack dump, jax trace
    start/stop, and cost attribution under /v1/traces/{rid}."""

    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.service import HttpService

        service = HttpService()
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"
        profiling.record_attribution("prof-rid-1", {
            "queue_wait_ms": 1.0, "device_step_share": 2.5,
            "decode_tokens": 8})
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(f"{base}/debug/profile") as r:
                    assert r.status == 200
                    body = await r.json()
                    assert body["loop"]["loop_lag"]["interval_s"] > 0
                    assert "engines" in body
                async with http.get(f"{base}/debug/profile/stacks") as r:
                    assert r.status == 200
                    assert r.content_type == "text/plain"
                async with http.get(f"{base}/v1/traces/prof-rid-1") as r:
                    assert r.status == 200
                    body = await r.json()
                    assert body["cost"]["device_step_share"] == 2.5
                    assert body["spans"] == []
                # jax.profiler capture round-trip (CPU backend works)
                import tempfile

                tdir = tempfile.mkdtemp(prefix="dynaprof-test-")
                async with http.post(f"{base}/debug/profile/start",
                                     json={"dir": tdir}) as r:
                    started = r.status == 200
                    if started:
                        body = await r.json()
                        assert body["dir"] == tdir
                if started:
                    # double-start is a clean 409, then stop succeeds
                    async with http.post(
                            f"{base}/debug/profile/start") as r:
                        assert r.status == 409
                    async with http.post(
                            f"{base}/debug/profile/stop") as r:
                        assert r.status == 200
                async with http.post(f"{base}/debug/profile/stop") as r:
                    assert r.status in (409, 500)
        finally:
            await service.stop()

    run_async(main())


def test_usage_cost_extension(monkeypatch):
    """DYN_PROF_USAGE gates the usage `cost` block; the Usage model
    round-trips it and exclude_none keeps OpenAI payloads clean."""
    from dynamo_tpu.llm.engines import usage_cost
    from dynamo_tpu.llm.protocols.openai import Usage, _merge_usage

    ctx = Context("usage-rid-1")
    profiling.record_attribution(ctx.id, {"decode_tokens": 4})
    assert usage_cost(ctx) is None          # default off
    monkeypatch.setenv("DYN_PROF_USAGE", "1")
    assert usage_cost(ctx) == {"decode_tokens": 4}
    assert usage_cost(Context("never-seen-rid")) is None

    u = Usage(prompt_tokens=3, completion_tokens=2, total_tokens=5,
              cost={"decode_tokens": 4})
    assert json.loads(u.model_dump_json())["cost"] == {"decode_tokens": 4}
    plain = Usage(prompt_tokens=1, completion_tokens=1, total_tokens=2)
    assert "cost" not in plain.model_dump(exclude_none=True)
    merged = _merge_usage(plain, u)
    assert merged.cost == {"decode_tokens": 4}


def test_attribution_ring_bounded(monkeypatch):
    monkeypatch.setenv("DYN_PROF_ATTR_RING", "4")
    for i in range(10):
        profiling.record_attribution(f"ring-{i}", {"i": i})
    assert profiling.request_attribution("ring-0") is None
    assert profiling.request_attribution("ring-9") == {"i": 9}
    assert len(profiling.attributions_snapshot(10 ** 6)) <= 4
