"""Sharded-execution tests on the virtual 8-device CPU mesh: TP+DP sharded
prefill/decode must produce the same logits as single-device execution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (KVCacheSpec, init_kv_cache, init_params,
                                     make_step_fns)
from dynamo_tpu.parallel.mesh import (MeshSpec, shard_batch, shard_kv_cache,
                                      shard_params)
from tests.test_model import PAGE, page_plan

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def tiny_cfg():
    return ModelConfig.tiny(num_heads=8, num_kv_heads=4, head_dim=8,
                            hidden_size=64)


def test_tp_dp_sharded_prefill_decode_matches_single_device():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prefill, decode = make_step_fns(cfg)

    B, T = 2, 12
    tokens = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (B, T + 1), 0, 500))
    pages = [[1, 2], [3, 4]]
    positions = np.broadcast_to(np.arange(T), (B, T)).copy()
    table = np.array([r + [0] * 6 for r in pages], np.int32)
    slots = page_plan(positions, pages)
    last = np.full((B,), T - 1, np.int32)

    # single-device reference
    kv_k, kv_v = init_kv_cache(cfg, KVCacheSpec(32, PAGE))
    ref_logits, kv_k, kv_v = prefill(
        params, jnp.asarray(tokens[:, :T]), jnp.asarray(positions), kv_k,
        kv_v, jnp.asarray(table), jnp.asarray(slots), jnp.asarray(last))
    dec_pos = np.full((B,), T, np.int32)
    dec_slots = page_plan(dec_pos[:, None].copy(), pages)[:, 0]
    ref_dec, _, _ = decode(params, jnp.asarray(tokens[:, T]),
                           jnp.asarray(dec_pos), kv_k, kv_v,
                           jnp.asarray(table), jnp.asarray(dec_slots))

    # sharded: data=2 x model=4
    mesh = MeshSpec(data=2, model=4).build()
    sparams = shard_params(params, cfg, mesh)
    skv_k, skv_v = init_kv_cache(cfg, KVCacheSpec(32, PAGE))
    skv_k, skv_v = shard_kv_cache(skv_k, skv_v, cfg, mesh)
    pre_in = shard_batch(mesh, tokens=tokens[:, :T], positions=positions,
                         page_table=table, flat_slots=slots, last_idx=last)
    s_logits, skv_k, skv_v = prefill(
        sparams, pre_in["tokens"], pre_in["positions"], skv_k, skv_v,
        pre_in["page_table"], pre_in["flat_slots"], pre_in["last_idx"])
    np.testing.assert_allclose(np.asarray(s_logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)

    dec_in = shard_batch(mesh, tokens=tokens[:, T], positions=dec_pos,
                         page_table=table, flat_slots=dec_slots)
    s_dec, _, _ = decode(sparams, dec_in["tokens"], dec_in["positions"],
                         skv_k, skv_v, dec_in["page_table"],
                         dec_in["flat_slots"])
    np.testing.assert_allclose(np.asarray(s_dec), np.asarray(ref_dec),
                               rtol=2e-4, atol=2e-4)


def test_moe_expert_parallel_sharding():
    cfg = ModelConfig.tiny(num_heads=8, num_kv_heads=4, head_dim=8,
                           hidden_size=64, num_experts=4,
                           num_experts_per_tok=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prefill, _ = make_step_fns(cfg)
    T = 8
    tokens = np.asarray(
        jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, 500))
    positions = np.arange(T)[None, :]
    table = np.array([[1, 0, 0, 0]], np.int32)
    slots = page_plan(positions.copy(), [[1]])
    last = np.array([T - 1], np.int32)

    kv_k, kv_v = init_kv_cache(cfg, KVCacheSpec(16, PAGE))
    ref, _, _ = prefill(params, jnp.asarray(tokens), jnp.asarray(positions),
                        kv_k, kv_v, jnp.asarray(table), jnp.asarray(slots),
                        jnp.asarray(last))

    # expert axis 2 x model 2 x data 2
    mesh = MeshSpec(data=2, model=2, expert=2).build()
    sparams = shard_params(params, cfg, mesh)
    kv_k2, kv_v2 = init_kv_cache(cfg, KVCacheSpec(16, PAGE))
    kv_k2, kv_v2 = shard_kv_cache(kv_k2, kv_v2, cfg, mesh)
    out, _, _ = prefill(sparams, jnp.asarray(tokens), jnp.asarray(positions),
                        kv_k2, kv_v2, jnp.asarray(table), jnp.asarray(slots),
                        jnp.asarray(last))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_engine_serves_on_sharded_mesh(run_async):
    """JaxEngine with a TP x DP mesh: params/KV sharded, generation must
    match the unsharded engine token-for-token (greedy)."""
    import numpy as np

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshSpec
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,))
    prompt = np.random.RandomState(3).randint(1, 500, 18).tolist()

    async def gen(engine):
        req = PreprocessedRequest(
            token_ids=prompt, sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    plain = run_async(gen(JaxEngine(cfg, ecfg, seed=0)))
    mesh = MeshSpec(model=2, data=2).build()
    sharded = run_async(gen(JaxEngine(cfg, ecfg, seed=0, mesh=mesh)))
    assert plain == sharded and len(plain) == 10
