"""dynablack: the incident flight recorder (ISSUE 19).

The acceptance contract: shadow rings are bounded and restart-safe,
the recorder debounces and evicts deterministically, the trigger
registry fires off the existing cold-path events (breaker open,
deadline storm), the HTTP surface serves bounded listings + the
incident table, the fleet-sim ``incident`` scenario produces a
byte-identical bundle per seed with rings from >= 2 workers, the e2e
path (severed request plane -> breaker open -> capture ->
GET /debug/incidents/{id} -> postmortem renderer) never errors, and
both Prometheus planes render hygienic exposition.
"""

import asyncio
import json
import re
import threading

import pytest

from dynamo_tpu.runtime import blackbox, guard, tracing


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Each test builds its own recorder; none leaks between tests."""
    blackbox.reset()
    yield
    blackbox.reset()


# ------------------------------------------------------------ shadow ring


def test_shadow_ring_bounded_windowed_and_anchored():
    clock, wall = FakeClock(100.0), FakeClock(1_000.0)
    ring = blackbox.ShadowRing("w0", maxlen=4, clock=clock, wall=wall)
    assert ring.anchors() == {"anchor_wall": 1_000.0,
                              "anchor_monotonic": 100.0}
    for i in range(6):
        clock.advance(1.0)
        ring.note("step", i=i)
    # bounded: the two oldest events rotated out
    assert len(ring) == 4
    events = ring.snapshot()
    assert [e["i"] for e in events] == [2, 3, 4, 5]
    # ts_ms is DERIVED from the wall anchor + the monotonic offset
    assert events[-1]["mono_ms"] == 6_000.0
    assert events[-1]["ts_ms"] == 1_000_000.0 + 6_000.0
    # window filter: only events inside the last 2 virtual seconds
    # (boundary inclusive: i=3 sits exactly on the cutoff)
    recent = ring.snapshot(window_s=2.0)
    assert [e["i"] for e in recent] == [3, 4, 5]
    # export is json.dumps-able whatever the fields held
    ring.note("weird", payload=object(), raw=b"\xff\xfe")
    json.dumps(ring.export())


def test_shadow_ring_restamp_clears_events_no_mono_aliasing():
    clock, wall = FakeClock(50.0), FakeClock(500.0)
    ring = blackbox.ShadowRing("w0", maxlen=16, clock=clock, wall=wall)
    clock.advance(10.0)
    ring.note("before", i=0)
    assert ring.snapshot()[0]["mono_ms"] == 10_000.0
    # restart: anchors restamp AND the ring clears, so a post-restart
    # event can never alias a pre-restart mono_ms on the new anchors
    clock.advance(5.0)
    wall.advance(100.0)
    ring.restamp()
    assert len(ring) == 0
    assert ring.anchors() == {"anchor_wall": 600.0,
                              "anchor_monotonic": 65.0}
    clock.advance(1.0)
    ring.note("after", i=1)
    (ev,) = ring.snapshot()
    assert ev["mono_ms"] == 1_000.0
    assert ev["ts_ms"] == 600_000.0 + 1_000.0


def test_shadow_ring_concurrent_writers_stay_bounded():
    ring = blackbox.ShadowRing("w0", maxlen=256)
    errors = []

    def writer(tid):
        try:
            for i in range(500):
                ring.note("ev", tid=tid, i=i)
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(ring) == 256
    json.dumps(ring.export())


# -------------------------------------------- telemetry-ring churn hygiene


def test_trace_ring_and_timeline_registry_churn():
    try:
        tracer = tracing.configure(sample=1.0, ring=8)
        for i in range(20):
            with tracer.start_span(f"s{i}"):
                pass
        # the span ring is bounded at the configured capacity
        assert len(tracer.snapshot()) == 8

        tl = tracing.StepTimeline(capacity=4)
        for i in range(10):
            tl.add("step", i=i)
        assert [e["i"] for e in tl.snapshot()] == [6, 7, 8, 9]
        tracing.register_timeline("churn-tl", tl)
        assert "churn-tl" in tracing.timelines_snapshot()
        # weakref registry: dropping the last strong ref evicts the entry
        del tl
        assert "churn-tl" not in tracing.timelines_snapshot()
    finally:
        tracing.configure()  # restore env defaults for later tests


def test_tracing_jsonl_export_round_trips(tmp_path):
    """The DL fix: span attributes are coerced JSON-safe at RECORD time,
    so the JSONL export parses with json.loads (never a repr-poisoned
    default=repr line) even for bytes/objects/int-keyed dicts."""
    path = tmp_path / "trace.jsonl"
    try:
        tracer = tracing.configure(sample=1.0, ring=32, jsonl=str(path))
        with tracer.start_span("weird", attributes={
                "raw": b"\xff\xfe", "obj": object(),
                "nested": {1: {"x": (1, 2)}}}) as sp:
            sp.set_attribute("late", {3, 1, 2})
        lines = [ln for ln in path.read_text().splitlines() if ln]
        assert lines
        rec = json.loads(lines[-1])
        assert rec["name"] == "weird"
        # bytes fell back to hex, the object became a repr STRING, the
        # int dict key became a string key — all plain JSON
        assert rec["attributes"]["raw"] == b"\xff\xfe".hex()
        assert isinstance(rec["attributes"]["obj"], str)
        assert rec["attributes"]["nested"]["1"] == {"x": [1, 2]}
        assert sorted(rec["attributes"]["late"]) == [1, 2, 3]
    finally:
        tracing.configure()


# --------------------------------------------------------- flight recorder


def _sim_recorder(clock, **kw):
    kw.setdefault("window_s", 10.0)
    kw.setdefault("cooldown_s", 60.0)
    kw.setdefault("out_dir", None)
    kw.setdefault("triggers", "all")
    kw.setdefault("include_process_state", False)
    return blackbox.FlightRecorder(clock=clock, wall=clock, **kw)


def test_recorder_trip_debounce_and_eviction():
    clock = FakeClock(1_000.0)
    rec = _sim_recorder(clock, max_incidents=2)
    rec.note("w0", "request", rid="r1")
    rec.note("w1", "request", rid="r2")

    b1 = rec.trip("manual", {"via": "test"})
    assert b1 is not None and b1["trigger"] == "manual"
    assert sorted(b1["workers"]) == ["w0", "w1"]
    assert rec.captures_total == 1
    # debounce: a second trip inside the cooldown is suppressed
    clock.advance(1.0)
    assert rec.trip("manual") is None
    assert rec.suppressed_total == 1
    assert 0 < rec.cooldown_remaining_s() <= 60.0
    # cooldown elapsed: captures again
    clock.advance(60.0)
    b2 = rec.trip("breaker_open", {"failures": 3})
    assert b2 is not None and b2["id"] != b1["id"]
    clock.advance(61.0)
    b3 = rec.trip("manual")
    # bounded incident table: the oldest bundle evicted at max_incidents=2
    assert rec.get(b1["id"]) is None
    assert rec.get(b2["id"]) is not None
    rows = rec.incidents_summary()
    assert [r["id"] for r in rows] == [b3["id"], b2["id"]]  # newest first


def test_recorder_trigger_filter_and_disabled():
    clock = FakeClock()
    rec = _sim_recorder(clock, triggers="breaker_open", cooldown_s=0.0)
    assert rec.trip("manual") is None          # filtered out
    assert rec.trip("breaker_open") is not None
    off = _sim_recorder(clock, window_s=0.0)
    assert not off.enabled
    assert off.trip("breaker_open") is None    # disarmed: never captures


def test_recorder_contribute_and_remote_stub():
    clock = FakeClock(10.0)
    rec = _sim_recorder(clock, cooldown_s=0.0)
    rec.note("local", "request", rid="r1")
    bundle = rec.trip("manual")
    ok = rec.contribute(bundle["id"],
                        {"sibling": {"anchors": {}, "events": []}},
                        origin="sibling")
    assert ok
    assert sorted(bundle["workers"]) == ["local", "sibling"]
    assert bundle["contributed"] == ["sibling"]
    assert not rec.contribute("nope", {}, origin="x")  # unknown id
    # a sibling's announcement opens a local stub carrying OUR rings,
    # bypassing the cooldown (the debounce belongs to the originator)
    stub = rec.observe_remote("incident-far", "slo_burn_rate",
                              origin="w9", at_ms=123.0)
    assert stub["remote"] and stub["origin"] == "w9"
    assert "local" in stub["workers"]
    assert rec.get("incident-far") is not None


def test_deadline_storm_trigger():
    clock = FakeClock(0.0)
    rec = _sim_recorder(clock, cooldown_s=0.0)
    blackbox.configure(recorder=rec)
    # 7 timeouts spread inside the window: no storm yet
    for _ in range(blackbox.STORM_N - 1):
        clock.advance(0.1)
        blackbox.note_deadline()
    assert rec.captures_total == 0
    clock.advance(0.1)
    blackbox.note_deadline()               # the Nth inside the window
    assert rec.captures_total == 1
    (row,) = rec.incidents_summary()
    assert row["trigger"] == "deadline_storm"
    # slow drip (outside STORM_WINDOW_S) never trips
    for _ in range(blackbox.STORM_N * 2):
        clock.advance(blackbox.STORM_WINDOW_S)
        blackbox.note_deadline()
    assert rec.captures_total == 1


def test_breaker_open_trips_the_recorder():
    clock = FakeClock()
    rec = _sim_recorder(clock, cooldown_s=0.0)
    blackbox.configure(recorder=rec)
    br = guard.CircuitBreaker(
        guard.BreakerConfig(threshold=2, probe_every=2), clock=clock)
    br.record_failure()
    assert rec.captures_total == 0         # below threshold: no trip
    br.record_failure()                    # closed -> open
    assert rec.captures_total == 1
    (row,) = rec.incidents_summary()
    assert row["trigger"] == "breaker_open"
    detail = rec.get(row["id"])["detail"]
    assert detail["failures"] == 2 and detail["opened_total"] == 1


def test_module_note_is_noop_without_armed_recorder():
    # nothing configured: the hot-path entry points must not build a
    # recorder as a side effect
    blackbox.note("w0", "ev", i=1)
    blackbox.note_deadline()
    clock = FakeClock()
    off = _sim_recorder(clock, window_s=0.0)
    blackbox.configure(recorder=off)
    blackbox.note("w0", "ev", i=2)
    assert len(off.rings) == 0             # disarmed recorder grew nothing


def test_capture_frame_infers_and_round_trips():
    from dynamo_tpu.runtime import wire
    frame = blackbox.capture_header("incident-1", "manual", "w0",
                                    at_ms=12.5,
                                    rings={"w0": {"anchors": {},
                                                  "events": []}})
    assert wire.infer_frame(frame).name == "blackbox.capture"
    assert wire.decoded(wire.BLACKBOX_CAPTURE, frame)["incident_id"] \
        == "incident-1"


# ------------------------------------------------------------ HTTP surface


def test_http_debug_surface_and_incident_endpoints(run_async, tmp_path):
    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.service import HttpService

        rec = blackbox.configure(window_s=30.0, cooldown_s=60.0,
                                 out_dir=str(tmp_path), triggers="all")
        blackbox.note("w0", "request", rid="r1")
        blackbox.note("w1", "request", rid="r2")
        service = HttpService()
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"
        try:
            async with aiohttp.ClientSession() as http:
                # bounded listings accept ?limit= / ?since_ms=
                async with http.get(f"{base}/v1/traces",
                                    params={"limit": 5,
                                            "since_ms": 0}) as r:
                    assert r.status == 200
                    body = await r.json()
                    assert {"traces", "engine_steps",
                            "engine_step_anchors"} <= set(body)
                async with http.get(f"{base}/v1/traces",
                                    params={"limit": "bogus"}) as r:
                    assert r.status == 400
                async with http.get(f"{base}/debug/profile/stacks",
                                    params={"limit": 10}) as r:
                    assert r.status == 200
                async with http.get(f"{base}/debug/profile/stacks",
                                    params={"since_ms": "junk"}) as r:
                    assert r.status == 400

                # manual capture
                async with http.post(f"{base}/debug/incidents/capture") as r:
                    assert r.status == 200
                    cap = await r.json()
                assert sorted(cap["workers"]) == ["w0", "w1"]
                # second capture inside the cooldown: 409 + Retry-After
                async with http.post(f"{base}/debug/incidents/capture") as r:
                    assert r.status == 409
                    assert int(r.headers["Retry-After"]) >= 1
                async with http.get(f"{base}/debug/incidents") as r:
                    listing = await r.json()
                assert listing["enabled"] and listing["captures_total"] == 1
                assert listing["incidents"][0]["id"] == cap["id"]
                async with http.get(
                        f"{base}/debug/incidents/{cap['id']}") as r:
                    assert r.status == 200
                    bundle = json.loads(await r.text())
                async with http.get(f"{base}/debug/incidents/nope") as r:
                    assert r.status == 404
        finally:
            await service.stop()

        # the bundle persisted under DYN_BLACKBOX_DIR, byte-identical to
        # the served serialization, and the postmortem renderer eats it
        persisted = (tmp_path / f"{cap['id']}.json").read_text()
        assert persisted == blackbox.render_bundle_json(bundle)
        from dynamo_tpu.admin.incident import render_postmortem
        text = render_postmortem(bundle)
        assert cap["id"] in text and "manual" in text
        assert rec.get(cap["id"]) is not None
        return True

    assert run_async(main())


# --------------------------------------------------- fleet-sim determinism


def test_fleet_incident_scenario_deterministic_bundle(run_async):
    """The tentpole acceptance: the deterministic fleet-sim ``incident``
    scenario trips a burn-rate capture AFTER the injected crash and
    produces a byte-identical bundle per seed, with shadow rings
    contributed by >= 2 sim workers over the real DCP fan-out."""
    from dynamo_tpu.fleet.harness import run_scenario
    from dynamo_tpu.fleet.scenarios import get_scenario

    r1 = run_async(run_scenario(get_scenario("incident"), seed=0))
    r2 = run_async(run_scenario(get_scenario("incident"), seed=0))

    b1, b2 = r1["incident"], r2["incident"]
    assert b1.get("trigger") == "slo_burn_rate", b1
    assert blackbox.render_bundle_json(b1) == blackbox.render_bundle_json(b2)
    sim_workers = [w for w in b1["workers"] if w.startswith("w")]
    assert len(sim_workers) >= 2
    # the contributions arrived over the wire, not by local aggregation
    assert len([c for c in b1["contributed"]
                if c.startswith("w")]) >= 2
    assert any(b1["workers"][w]["events"] for w in sim_workers)
    # the crash fault the alert postdates is on the harness ring
    harness_events = b1["workers"]["sim-harness"]["events"]
    assert any(e["kind"] == "fault" for e in harness_events)
    # the renderer consumes the sim bundle without error
    from dynamo_tpu.admin.incident import render_postmortem
    assert "slo_burn_rate" in render_postmortem(b1)


# ------------------------------------------------------------------- e2e


def test_e2e_breaker_open_capture_served_and_rendered(run_async, tmp_path):
    """Severed request plane -> breaker opens -> the breaker_open trigger
    captures on the live recorder -> the bundle is served by
    GET /debug/incidents/{id} -> the admin renderer renders it. The
    whole dynablack loop against the real DCP + HTTP stack."""

    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        blackbox.configure(window_s=60.0, cooldown_s=120.0,
                           out_dir=str(tmp_path), triggers="all")
        drt = await DistributedRuntime.detached()
        service = HttpService()
        await service.start(host="127.0.0.1", port=0)
        try:
            async def handler(request, ctx):
                yield {"ok": True}

            ep = drt.namespace("bb").component("w").endpoint("gen")
            handle = await ep.serve(handler)
            client = await ep.client()
            await client.wait_for_instances(timeout=5)
            wid = client.instance_ids()[0]
            blackbox.note(f"{wid:x}", "serving", state="up")

            # sever the worker's request plane: unsubscribe the handlers
            # but keep the discovery record (crashed-but-leased worker)
            for sid in handle._sids:
                await drt.dcp.unsubscribe(sid)
            handle._sids.clear()

            client.retry = guard.RetryPolicy(max_attempts=1)
            for _ in range(client.breakers.cfg.threshold):
                with pytest.raises(Exception):
                    await client.round_robin({"x": 1}, timeout=0.5)
            assert client.breakers.get("request", wid).state \
                == guard.BREAKER_OPEN

            rec = blackbox.get_recorder()
            rows = [r for r in rec.incidents_summary()
                    if r["trigger"] == "breaker_open"]
            assert rows, "breaker open never tripped a capture"
            iid = rows[0]["id"]

            base = f"http://127.0.0.1:{service.port}"
            async with aiohttp.ClientSession() as http:
                async with http.get(f"{base}/debug/incidents/{iid}") as r:
                    assert r.status == 200
                    bundle = json.loads(await r.text())
            assert bundle["trigger"] == "breaker_open"
            assert f"{wid:x}" in bundle["workers"]
            # live capture folds the process telemetry planes
            assert {"guard_counters", "breakers", "caches",
                    "loop_lag"} <= set(bundle["telemetry"])
            assert (tmp_path / f"{iid}.json").exists()

            from dynamo_tpu.admin.incident import render_postmortem
            text = render_postmortem(bundle)
            assert "breaker_open" in text and f"{wid:x}" in text

            await client.close()
        finally:
            await service.stop()
            await drt.shutdown()
        return True

    assert run_async(main())


# --------------------------------------------- Prometheus exposition hygiene


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)$')
_LABELS_RE = re.compile(
    r'^\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\}$')


def _check_exposition(text: str, plane: str):
    help_seen, type_seen = {}, {}
    sample_names = set()
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith("# HELP "):
            _, _, rest = ln.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            assert help_seen.get(name, help_) == help_, \
                f"{plane}: conflicting HELP for {name}"
            help_seen[name] = help_
            continue
        if ln.startswith("# TYPE "):
            _, _, rest = ln.partition("# TYPE ")
            name, _, typ = rest.partition(" ")
            assert typ in ("counter", "gauge", "histogram", "summary"), \
                f"{plane}: bad TYPE {typ!r} for {name}"
            assert type_seen.get(name, typ) == typ, \
                f"{plane}: conflicting TYPE for {name}"
            type_seen[name] = typ
            continue
        if ln.startswith("#"):
            continue
        m = _SAMPLE_RE.match(ln)
        assert m, f"{plane}: malformed sample line {ln!r}"
        name, labels, value = m.groups()
        if labels:
            assert _LABELS_RE.match(labels), \
                f"{plane}: malformed labels in {ln!r}"
        float(value)  # parses as a number (inf/nan included)
        sample_names.add(name)
    # every sample belongs to a declared family (histogram suffixes fold)
    for name in sample_names:
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if family not in type_seen and name.endswith(suffix):
                family = name[:-len(suffix)]
        assert family in type_seen, \
            f"{plane}: sample {name} has no TYPE declaration"
    # dyn_* charset (the regex above enforced it; keep the explicit gate)
    for name in sample_names | set(type_seen):
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name)
    return type_seen


def test_prometheus_exposition_hygiene_both_planes():
    from dynamo_tpu.llm.http.metrics import Metrics
    from dynamo_tpu.metrics.component import MetricsAggregator

    m = Metrics()
    m.requests_total[("m1", "completions", "unary", "200")] += 1
    m.inflight["m1"] = 2
    m.observe_duration("m1", 0.25)
    m.observe_ttft("m1", 0.1)
    m.itl.observe("m1", 0.01)
    m.stage.observe("prefill", 0.2)
    m.count_output_tokens("m1", 7)
    frontend_types = _check_exposition(m.render(), "frontend")
    assert any(n.startswith("dyn_") for n in frontend_types)

    agg = MetricsAggregator(None, "ns", "c")
    agg_types = _check_exposition(agg.render_prometheus(), "aggregator")
    assert any(n.startswith("dyn_") for n in agg_types)
