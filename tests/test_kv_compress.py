"""int8 KV page compression at the slow boundaries (engine/kv_compress):
roundtrip error bounds, the compressed host tier end-to-end through the
engine, and the compressed disagg transfer wire format. Reference
analog: KV compression at the offload/transfer boundary (LMCache-style)
— lossy, so everything here is opt-in and tested with tolerances, not
bit-identity."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.kv_compress import (dequantize_pages,
                                           dequantize_pages_np,
                                           quantize_pages,
                                           quantize_pages_np)


def test_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    pages = (rng.randn(2, 3, 2, 4, 16) * 0.5).astype(np.float32)
    for q, s in (quantize_pages_np(pages),
                 [np.asarray(x) for x in quantize_pages(
                     jnp.asarray(pages))]):
        back = dequantize_pages_np(q, s, np.float32)
        err = np.abs(back - pages)
        assert (err <= np.asarray(s) / 2 + 1e-7).all()
        assert np.asarray(q).dtype == np.int8
    # device and host variants agree exactly
    qd, sd = quantize_pages(jnp.asarray(pages))
    qh, sh = quantize_pages_np(pages)
    np.testing.assert_array_equal(np.asarray(qd), qh)
    np.testing.assert_allclose(np.asarray(sd), sh, rtol=1e-6)
    # jit dequant == np dequant
    np.testing.assert_allclose(np.asarray(dequantize_pages(qd, sd)),
                               dequantize_pages_np(qh, sh, np.float32),
                               rtol=1e-6)


def _engine(host_pages=0, host_tier_int8=False):
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=24, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,),
                        host_pages=host_pages, watermark_pages=2,
                        host_tier_int8=host_tier_int8)
    return JaxEngine(cfg, ecfg, seed=0)


async def _gen(engine, prompt, n=8):
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=prompt, sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
        eos_token_ids=[])
    toks = []
    async for out in engine.generate(req, Context()):
        toks.extend(out.token_ids)
        if out.finish_reason:
            break
    return toks


def test_int8_host_tier_end_to_end(run_async):
    """Evict → restore through the COMPRESSED tier: the restore counts
    as a prefix hit and the continuation matches the uncompressed tier's
    (tiny model, short continuation — int8 KV noise does not flip greedy
    argmaxes here; the property pinned is 'restored content, not
    garbage', with the exact-tier run as the reference)."""
    engine = _engine(host_pages=64, host_tier_int8=True)
    assert engine.host_k.dtype == np.int8
    assert engine.host_k_s is not None

    async def scenario():
        rng = np.random.RandomState(0)
        prompt_a = rng.randint(1, 500, 24).tolist()
        first = await _gen(engine, prompt_a)
        for i in range(4):
            await _gen(engine, rng.randint(1, 500, 24).tolist())
        hits_before = engine.prefix_hit_tokens_total
        again = await _gen(engine, prompt_a)
        await engine.stop()
        return first, again, engine.prefix_hit_tokens_total - hits_before

    first, again, hits = run_async(scenario())
    assert len(first) == 8
    assert hits > 0 and engine.restore_pages_total > 0
    assert first == again


def test_int8_tier_host_pool_half_bytes():
    e8 = _engine(host_pages=16, host_tier_int8=True)
    e16 = _engine(host_pages=16, host_tier_int8=False)
    compressed = e8.host_k.nbytes + e8.host_k_s.nbytes
    assert compressed < e16.host_k.nbytes * 0.6  # ~0.53 at hd=16


def test_transfer_wire_int8(run_async):
    """KvTransferServer/Client with compress=True: the body carries int8
    + scales (~half the bytes), the receiver restores into its pool and
    resolves the waiter; content matches within the quantization bound."""
    from dynamo_tpu.llm.disagg.transfer import (KvTransferClient,
                                                KvTransferServer)

    class SinkEngine:
        def __init__(self):
            self.got = None

        async def inject_pages(self, page_ids, k, v):
            self.got = (list(page_ids), np.asarray(k, np.float32),
                        np.asarray(v, np.float32))

    async def main():
        sink = SinkEngine()
        server = KvTransferServer(sink)
        await server.start(host="127.0.0.1")
        rng = np.random.RandomState(1)
        shape = (2, 3, 2, 4, 16)
        k = (rng.randn(*shape) * 0.3).astype(np.float32)
        v = (rng.randn(*shape) * 0.3).astype(np.float32)

        client = KvTransferClient("127.0.0.1", server.port)
        fut = server.expect("r1")
        await client.send_kv("r1", [5, 6, 7], k, v, first_token=42,
                             compress=True)
        tok = await asyncio.wait_for(fut, 10)
        client.close()
        await server.stop()
        return sink.got, tok, server.bytes_ingested, k, v

    got, tok, nbytes, k, v = run_async(main())
    assert tok == 42
    page_ids, gk, gv = got
    assert page_ids == [5, 6, 7]
    # half the uncompressed bytes (2 pools x (int8 + f32/hd scales))
    raw = 2 * np.prod((2, 3, 2, 4, 16)) * 4  # f32 sender arrays
    assert nbytes < raw * 0.6
    # error bounded by per-row scale: |x - deq(q)| <= amax/254 + eps
    for a, b in ((k, gk), (v, gv)):
        bound = np.max(np.abs(a), axis=-1, keepdims=True) / 254 + 1e-6
        assert (np.abs(a - b) <= bound).all()


def test_transfer_wire_raw_still_exact(run_async):
    """compress=False keeps the original bit-exact wire format."""
    from dynamo_tpu.llm.disagg.transfer import (KvTransferClient,
                                                KvTransferServer)

    class SinkEngine:
        def __init__(self):
            self.got = None

        async def inject_pages(self, page_ids, k, v):
            self.got = (np.asarray(k), np.asarray(v))

    async def main():
        sink = SinkEngine()
        server = KvTransferServer(sink)
        await server.start(host="127.0.0.1")
        rng = np.random.RandomState(2)
        k = rng.randn(1, 2, 2, 4, 8).astype(np.float32)
        v = rng.randn(1, 2, 2, 4, 8).astype(np.float32)
        client = KvTransferClient("127.0.0.1", server.port)
        fut = server.expect("r2")
        await client.send_kv("r2", [1, 2], k, v, first_token=7)
        await asyncio.wait_for(fut, 10)
        client.close()
        await server.stop()
        return k, v, sink.got

    k, v, (gk, gv) = run_async(main())
    np.testing.assert_array_equal(k, gk)
    np.testing.assert_array_equal(v, gv)


def test_prefill_worker_env_opt_in(monkeypatch):
    from dynamo_tpu.llm.disagg import PrefillWorker

    class Drt:
        dcp = None

    monkeypatch.setenv("DYN_KV_TRANSFER_INT8", "1")
    assert PrefillWorker(Drt(), None).compress_kv
    monkeypatch.delenv("DYN_KV_TRANSFER_INT8")
    assert not PrefillWorker(Drt(), None).compress_kv
    assert PrefillWorker(Drt(), None, compress_kv=True).compress_kv
