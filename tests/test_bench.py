"""bench.py is driver-facing and load-bearing (one bad code path costs a
round's only hardware evidence — the r3 rc=1 incident was bench.py's own
probe). These tests pin the probe decision table, the error-record
contract, the watchdog, and measure()'s aggregation — all with fakes; no
TPU (VERDICT r4 weak #8 / task 9)."""

import asyncio
import io
import json
import os
import signal
import subprocess
import sys
import types
from contextlib import redirect_stdout
from dataclasses import dataclass, field
from typing import List, Optional

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def make_args(**over):
    base = dict(sweep=None, scenario="sharegpt", isl=512, osl=128,
                requests=64, concurrency=32, model="1b", dtype="bf16",
                users=16, turns=4, host_pages=0, disagg_threshold=256)
    base.update(over)
    return types.SimpleNamespace(**base)


# ------------------------------------------------------------ emit contract


def record_of(fn, *a):
    buf = io.StringIO()
    with redirect_stdout(buf):
        fn(*a)
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    assert len(lines) == 1, f"must print exactly ONE record: {lines}"
    return json.loads(lines[-1])


@pytest.mark.parametrize("over,unit", [
    ({}, "tok/s"),
    ({"scenario": "multiturn"}, "ms"),
    ({"scenario": "disagg"}, "ratio"),
    ({"sweep": "32:64:4"}, "tok/s"),
    ({"sweep": "32:64:4", "scenario": "multiturn"}, "tok/s"),  # sweep wins
    ({"model": "8b", "dtype": "int8"}, "tok/s"),
    ({"scenario": "sharded", "dp_replicas": 2, "mesh": "model=2"},
     "tok/s"),
    ({"scenario": "failover"}, "tok/s"),
    ({"scenario": "hotpath", "decode_steps": 16}, "ms"),
    ({"scenario": "hotpath", "decode_steps": 16,
      "hotpath_legacy": True}, "ms"),
])
def test_emit_unavailable_matches_metric_name(over, unit):
    """A chip-unavailable record must carry the SAME metric label (and a
    consistent unit) as the success record for the same invocation, or
    the driver cannot pair them."""
    args = make_args(**over)
    rec = record_of(bench.emit_unavailable, args, "test reason")
    assert rec["metric"] == bench.metric_name(args)
    assert rec["unit"] == unit
    assert rec["value"] is None and "chip unavailable" in rec["error"]


def test_int8_model_tag_in_label():
    assert "8b-int8 llama" in bench.metric_name(
        make_args(model="8b", dtype="int8"))
    assert "1b llama" in bench.metric_name(make_args())


# ------------------------------------------------------------- probe paths


@dataclass
class FakeProc:
    out: str = ""
    err: str = ""
    returncode: int = 0
    hang: bool = False
    terminated: List[str] = field(default_factory=list)
    _woken: bool = False

    def communicate(self, timeout=None):
        if self.hang and not self._woken:
            raise subprocess.TimeoutExpired("probe", timeout)
        return self.out, self.err

    def terminate(self):
        self.terminated.append("SIGTERM")
        self._woken = True  # child dies promptly after SIGTERM

    def kill(self):  # pragma: no cover - must never be called
        raise AssertionError("probe used SIGKILL — wedges the relay")


def probe_with(monkeypatch, proc):
    monkeypatch.setattr(subprocess, "Popen", lambda *a, **k: proc)
    return bench.probe_backend(0.1)


def test_probe_timeout_uses_sigterm_only(monkeypatch):
    proc = FakeProc(hang=True)
    ok, reason = probe_with(monkeypatch, proc)
    assert not ok and "relay wedged" in reason
    assert proc.terminated == ["SIGTERM"]


def test_probe_nonzero_rc_reports_stderr_tail(monkeypatch):
    ok, reason = probe_with(monkeypatch, FakeProc(
        returncode=1, err="Trace...\nRuntimeError: tunnel refused"))
    assert not ok and "tunnel refused" in reason


def test_probe_rejects_silent_cpu_fallback(monkeypatch):
    ok, reason = probe_with(monkeypatch, FakeProc(
        out=json.dumps({"n": 1, "platform": "cpu"})))
    assert not ok and "CPU" in reason


def test_probe_unparseable_output(monkeypatch):
    ok, reason = probe_with(monkeypatch, FakeProc(out="garbage"))
    assert not ok and "unparseable" in reason


def test_probe_accepts_tpu(monkeypatch):
    ok, reason = probe_with(monkeypatch, FakeProc(
        out=json.dumps({"n": 1, "platform": "axon"})))
    assert ok and reason == ""


# ------------------------------------------------- main() failure envelopes


def run_main(monkeypatch, argv, **patches):
    monkeypatch.setattr(sys, "argv", ["bench.py"] + argv)
    for name, val in patches.items():
        monkeypatch.setattr(bench, name, val)
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.main()
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    assert len(lines) == 1, f"driver expects ONE stdout line: {lines}"
    return json.loads(lines[-1])


def test_main_probe_failure_emits_record(monkeypatch):
    rec = run_main(monkeypatch, [],
                   probe_backend=lambda t: (False, "no tunnel"))
    assert rec["value"] is None and "no tunnel" in rec["error"]
    assert rec["metric"] == bench.metric_name(make_args())


def test_main_midrun_exception_emits_record(monkeypatch):
    def boom(args):
        raise RuntimeError("relay dropped mid-run")

    rec = run_main(monkeypatch, [],
                   probe_backend=lambda t: (True, ""),
                   arm_watchdog=lambda a, b: None,
                   _run_scenario=boom)
    assert rec["value"] is None
    assert "RuntimeError: relay dropped mid-run" in rec["error"]


def test_main_success_prints_scenario_record(monkeypatch):
    good = {"metric": "m", "value": 123.0, "unit": "tok/s",
            "vs_baseline": 1.0}
    rec = run_main(monkeypatch, [],
                   probe_backend=lambda t: (True, ""),
                   arm_watchdog=lambda a, b: None,
                   _run_scenario=lambda a: dict(good))
    assert rec == good


# ---------------------------------------------------------------- watchdog


def test_watchdog_fires_record_then_sigterm():
    """True e2e in a subprocess: an over-budget bench must still print
    the ONE parseable record, then stop itself with SIGTERM (never
    SIGKILL — relay discipline)."""
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, time, types\n"
        "args = types.SimpleNamespace(sweep=None, scenario='sharegpt',\n"
        "    isl=1, osl=1, requests=1, concurrency=1, model='tiny',\n"
        "    dtype='bf16', users=0, turns=0, host_pages=0,\n"
        "    disagg_threshold=0)\n"
        "bench.arm_watchdog(args, 0.2)\n"
        "time.sleep(60)\n" % REPO)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=45)
    assert proc.returncode == -signal.SIGTERM, (proc.returncode,
                                                proc.stderr[-500:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["value"] is None and "wall budget" in rec["error"]


# ------------------------------------------------------ measure() contract


class FakeEngine:
    """Yields `chunks` per request: list of (token_ids, finish_reason,
    delay_s) — enough to script TTFT/ITL/error shapes."""

    def __init__(self, chunks):
        self.chunks = chunks

    async def generate(self, req, ctx):
        for token_ids, fin, delay in self.chunks:
            await asyncio.sleep(delay)
            yield types.SimpleNamespace(token_ids=token_ids,
                                        finish_reason=fin)


def test_measure_aggregates_and_raw_itl():
    eng = FakeEngine([
        ([1], None, 0.02),          # first token: TTFT ~20ms
        ([2, 3], None, 0.04),       # chunk gap 40ms
        ([4, 5], "stop", 0.04),     # chunk gap 40ms
    ])
    rep = asyncio.run(bench.measure(eng, [([7] * 4, 5)] * 3, 2))
    assert rep["requests"] == 3 and rep["errors"] == 0
    assert rep["ttft_p50_ms"] and rep["ttft_p50_ms"] >= 15
    # window-amortized: (last-first)/(n-1) = 80ms/4 = ~20ms
    assert 10 <= rep["itl_p50_ms"] <= 40
    # raw chunk gaps: ~40ms each — the un-amortized truth
    assert 30 <= rep["itl_raw_chunk_p50_ms"] <= 80
    assert rep["itl_raw_chunk_p99_ms"] >= rep["itl_raw_chunk_p50_ms"]


def test_measure_error_rows_excluded():
    eng = FakeEngine([([1], "error", 0.0)])
    rep = asyncio.run(bench.measure(eng, [([7], 3)] * 2, 2))
    assert rep["errors"] == 2 and rep["requests"] == 0
    assert rep["output_tok_per_s"] == 0.0


def test_measure_request_timeout_is_error_row(monkeypatch):
    monkeypatch.setenv("DYN_BENCH_REQ_TIMEOUT", "0.3")

    class HangingEngine:
        async def generate(self, req, ctx):
            yield types.SimpleNamespace(token_ids=[1], finish_reason=None)
            await asyncio.sleep(60)

    rep = asyncio.run(bench.measure(HangingEngine(), [([7], 3)], 1))
    assert rep["errors"] == 1 and rep["requests"] == 0


def test_disagg_label_reflects_transfer_int8(monkeypatch):
    args = make_args(scenario="disagg")
    base = bench.metric_name(args)
    monkeypatch.setenv("DYN_KV_TRANSFER_INT8", "1")
    assert "kv-int8" in bench.metric_name(args)
    monkeypatch.delenv("DYN_KV_TRANSFER_INT8")
    assert bench.metric_name(args) == base
    assert "kv-chunks 0,4" in bench.metric_name(
        make_args(scenario="disagg", kv_chunk_pages="0,4"))


def test_disagg_streaming_smoke_cpu():
    """Tier-1 CPU smoke for the streaming transfer plane through the REAL
    disagg bench path: a bulk leg (chunk_pages=0) and a chunked leg on the
    same engines, each reporting the per-stage extract/compress/wire/
    inject breakdown. Pins the sweep plumbing, the per-leg stat deltas,
    and that multi-chunk streams actually went over the wire."""
    args = make_args(scenario="disagg", model="tiny", requests=4,
                     concurrency=2, isl=96, osl=4, seed=0,
                     decode_steps=2, disagg_threshold=16,
                     kv_chunk_pages="0,2", prefill_token_budget=None,
                     host_pages=0, host_tier_int8=False, max_batch=None,
                     spec=False, dtype="bf16")
    report = asyncio.run(bench.run_disagg(args))
    legs = report["disagg_legs"]
    assert [leg["kv_chunk_pages"] for leg in legs] == [0, 2]
    bulk, chunked = legs
    for leg in legs:
        assert leg["errors"] == 0
        assert leg["remote_prefills"] > 0
        assert leg["remote_fallbacks"] == 0
        stages = leg["transfer_stages"]
        assert stages["extract_s"] > 0 and stages["inject_s"] > 0
        assert stages["send_wall_s"] > 0
    # bulk mode sends exactly one frame per request → no chunk frames
    assert bulk["transfer_stages"]["chunks_sent"] == 0
    # 96-token prompts = 6 pages of 16 → ≥3 chunk frames per request
    assert (chunked["transfer_stages"]["chunks_sent"]
            >= 3 * chunked["remote_prefills"])
    assert chunked["transfer_pages"] > 0
    # both legs moved the same pages per request (same workload shape)
    assert report["disagg_over_agg_req_per_s"] > 0
