"""Logprobs: requested via the OpenAI fields since round 1 but computed
nowhere until round 5 (sampling.compute_logprobs had no callers). Pins:
the math vs the model's own logits, engine end-to-end attachment across
the fused window AND the prefill first token, and the OpenAI response
shapes (chat content entries / legacy completions lists)."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.sampling import logprob_aux
from dynamo_tpu.llm.protocols.common import (OutputOptions,
                                             PreprocessedRequest,
                                             SamplingOptions,
                                             StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context


def test_logprob_aux_math():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(3, 50).astype(np.float32) * 2)
    chosen = jnp.asarray([7, 0, 49])
    lp, tv, ti = logprob_aux(logits, chosen, 4)
    ref = np.log(np.exp(np.asarray(logits))
                 / np.exp(np.asarray(logits)).sum(-1, keepdims=True))
    np.testing.assert_allclose(np.asarray(lp),
                               ref[np.arange(3), np.asarray(chosen)],
                               rtol=1e-5, atol=1e-5)
    # top entries are the 4 largest logprobs, descending
    for b in range(3):
        want = np.sort(ref[b])[::-1][:4]
        np.testing.assert_allclose(np.asarray(tv[b]), want, rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(tv[b])[0],
                                   ref[b, np.asarray(ti[b])[0]],
                                   rtol=1e-5, atol=1e-5)


def _engine():
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine

    cfg = ModelConfig.tiny()
    return JaxEngine(cfg, EngineConfig(
        page_size=8, num_pages=64, max_batch=4, prefill_chunk=32,
        prefill_buckets=(32,), batch_buckets=(4,), page_buckets=(16,),
        decode_steps=4, max_top_logprobs=3), seed=0), cfg


def test_engine_emits_logprobs_end_to_end(run_async):
    """Greedy with logprobs=2: every emitted token carries its logprob
    and 2 top alternatives; the chosen greedy token IS the top-1, so its
    logprob equals the best alternative's. Covers the prefill first
    token (window j=None path) and K=4 window steps."""
    eng, cfg = _engine()

    async def go():
        req = PreprocessedRequest(
            token_ids=[3, 1, 4, 1, 5, 9], sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=9, ignore_eos=True),
            output=OutputOptions(logprobs=2), eos_token_ids=[])
        outs = []
        async for out in eng.generate(req, Context()):
            outs.append(out)
            if out.finish_reason:
                break
        await eng.stop()
        return outs

    outs = run_async(go())
    toks = [t for o in outs for t in o.token_ids]
    assert len(toks) == 9
    per_tok = [(t, o.logprobs[k], o.top_logprobs[k])
               for o in outs if o.logprobs
               for k, t in enumerate(o.token_ids)]
    assert len(per_tok) == 9  # every token has an entry
    for tok, lp, top in per_tok:
        assert lp <= 0.0
        assert len(top) == 2  # requested 2 of max_top_logprobs=3
        best = max(top.values())
        # greedy: the sampled token is the argmax → its logprob is the
        # top-1 value (ties broken identically by the same top_k)
        assert abs(lp - best) < 1e-5
        assert tok in top or abs(lp - best) < 1e-5


def test_engine_no_logprobs_fields_absent(run_async):
    eng, cfg = _engine()

    async def go():
        req = PreprocessedRequest(
            token_ids=[1, 2, 3], sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=5, ignore_eos=True),
            eos_token_ids=[])
        outs = []
        async for out in eng.generate(req, Context()):
            outs.append(out)
            if out.finish_reason:
                break
        await eng.stop()
        return outs

    outs = run_async(go())
    assert all(o.logprobs is None and o.top_logprobs is None for o in outs)


def test_http_chat_and_completion_logprob_shapes(run_async):
    """OpenAI response shapes through the HTTP frontend (echo-core chain
    computes no logprobs, so drive the real-engine run.py chain):
    chat: choices[].logprobs.content[] entries with token/logprob/bytes/
    top_logprobs; completions: parallel tokens/token_logprobs/
    top_logprobs/text_offset lists."""
    import aiohttp

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.echo import EchoEngineCore  # noqa: F401
    from dynamo_tpu.llm.engines import (LocalChatChain,
                                        LocalCompletionChain)
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    eng, cfg = _engine()
    mdc = ModelDeploymentCard(name="m", kv_block_size=8)

    async def main():
        service = HttpService()
        service.manager.add_chat_model("m", LocalChatChain(mdc, eng))
        service.manager.add_completions_model(
            "m", LocalCompletionChain(mdc, eng))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            body = {"model": "m", "max_tokens": 4,
                    "logprobs": True, "top_logprobs": 2,
                    "messages": [{"role": "user", "content": "hi"}]}
            async with http.post(f"{base}/v1/chat/completions",
                                 json=body) as r:
                assert r.status == 200, await r.text()
                chat = await r.json()
            cbody = {"model": "m", "prompt": "hello", "max_tokens": 4,
                     "logprobs": 2}
            async with http.post(f"{base}/v1/completions",
                                 json=cbody) as r:
                assert r.status == 200, await r.text()
                comp = await r.json()
        await service.stop()
        await eng.stop()
        return chat, comp

    chat, comp = run_async(main())
    clp = chat["choices"][0].get("logprobs")
    assert clp is not None and len(clp["content"]) == 4
    e = clp["content"][0]
    assert set(e) >= {"token", "logprob", "bytes", "top_logprobs"}
    assert len(e["top_logprobs"]) == 2
    assert e["logprob"] <= 0.0
    lp = comp["choices"][0].get("logprobs")
    assert lp is not None
    assert len(lp["tokens"]) == len(lp["token_logprobs"]) == 4
    assert len(lp["top_logprobs"]) == 4
    # the legacy format keys alternatives by token STRING — distinct ids
    # can decode to the same string (byte tokenizer), so >= 1, <= 2
    assert all(1 <= len(d) <= 2 for d in lp["top_logprobs"])
    assert lp["text_offset"][0] == 0
    assert all(isinstance(t, str) for t in lp["tokens"])


def test_top_logprobs_requires_logprobs_flag(run_async):
    """OpenAI validation: top_logprobs without logprobs=true → 400; out
    of range → 400."""
    import aiohttp

    from dynamo_tpu.engine.echo import EchoEngineCore
    from dynamo_tpu.llm.engines import LocalChatChain
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    async def main():
        service = HttpService()
        mdc = ModelDeploymentCard(name="m", kv_block_size=8)
        service.manager.add_chat_model(
            "m", LocalChatChain(mdc, EchoEngineCore(delay_ms=0)))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"
        out = {}
        async with aiohttp.ClientSession() as http:
            msgs = [{"role": "user", "content": "x"}]
            async with http.post(f"{base}/v1/chat/completions", json={
                    "model": "m", "messages": msgs,
                    "top_logprobs": 3}) as r:
                out["no_flag"] = r.status
            async with http.post(f"{base}/v1/chat/completions", json={
                    "model": "m", "messages": msgs, "logprobs": False,
                    "top_logprobs": 3}) as r:
                out["false_flag"] = r.status
            async with http.post(f"{base}/v1/chat/completions", json={
                    "model": "m", "messages": msgs, "logprobs": True,
                    "top_logprobs": 50}) as r:
                out["too_many"] = r.status
        await service.stop()
        return out

    out = run_async(main())
    assert out == {"no_flag": 400, "false_flag": 400, "too_many": 400}
