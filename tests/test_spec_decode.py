"""Self-speculative decoding: n-gram drafting, batched greedy verify,
bypass semantics, and page accounting.

The correctness bar (ISSUE 1): greedy outputs must be TOKEN-IDENTICAL to
the non-speculative path — speculation may only change how many device
steps the same tokens take — and sampled/penalty/logprobs requests must
transparently bypass the speculative arm.
"""

import asyncio

import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.engine.spec_decode import propose_ngram_draft
from dynamo_tpu.llm.protocols.common import (OutputOptions,
                                             PreprocessedRequest,
                                             SamplingOptions, StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime import Context

MOTIF = [11, 45, 7, 102, 33, 91, 5, 68, 23, 77, 14, 50]


def mk_engine(**eng_kw):
    cfg = ModelConfig.tiny()
    defaults = dict(page_size=8, num_pages=128, max_batch=8,
                    prefill_chunk=32)
    defaults.update(eng_kw)
    return JaxEngine(cfg, EngineConfig(**defaults), seed=0)


def mk_request(tokens, max_tokens=8, logprobs=None, ignore_eos=True,
               **sampling):
    return PreprocessedRequest(
        token_ids=list(tokens), sampling=SamplingOptions(**sampling),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=ignore_eos),
        output=OutputOptions(logprobs=logprobs), eos_token_ids=[258])


async def collect(engine, req, ctx=None):
    ctx = ctx or Context()
    toks, finish, lps = [], None, []
    async for out in engine.generate(req, ctx):
        toks.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finish_reason:
            finish = out.finish_reason
            break
    return toks, finish, lps


# ------------------------------------------------------------ the drafter


def test_ngram_drafter_matches_and_caps():
    # ...A B C D...A B C -> proposes D (and what follows it)
    hist = [1, 2, 3, 4, 5, 6, 9, 9, 1, 2, 3]
    assert propose_ngram_draft(hist, 4, ngram_max=3) == [4, 5, 6, 9]
    assert propose_ngram_draft(hist, 2, ngram_max=3) == [4, 5]
    # no earlier occurrence of any suffix n-gram -> no draft
    assert propose_ngram_draft([1, 2, 3, 4], 4, ngram_max=3) == []
    # too short / no budget
    assert propose_ngram_draft([1], 4, ngram_max=3) == []
    assert propose_ngram_draft(hist, 0, ngram_max=3) == []


def test_ngram_drafter_prefers_most_recent_continuation():
    # "7" occurs twice with different continuations; the LATEST wins
    hist = [7, 1, 1, 5, 7, 2, 2, 6, 7]
    assert propose_ngram_draft(hist, 2, ngram_max=3) == [2, 2]


def test_ngram_drafter_periodic_suffix():
    # the suffix may overlap its own earlier occurrence (pure
    # repetition), and a short-period loop still fills the whole draft:
    # the drafter prefers hits with a full continuation over the most
    # recent (truncated) one
    assert propose_ngram_draft([3] * 8, 2, ngram_max=3) == [3, 3]
    # no hit can supply the full draft -> longest available continuation
    assert propose_ngram_draft([3, 3, 3, 3], 2, ngram_max=3) == [3]


# ----------------------------------------------------- greedy correctness


def test_spec_greedy_token_identity(run_async):
    """Speculation on/off must produce byte-identical greedy streams —
    on repetitive prompts (drafts accept) and non-repetitive ones
    (drafts mostly reject), across many decode steps."""

    async def main():
        prompts = [(MOTIF * 6)[:72], list(range(10, 18)),
                   list(range(10, 20)) * 3]
        base = mk_engine()
        ref = [await collect(base, mk_request(p, max_tokens=96))
               for p in prompts]
        await base.stop()
        spec = mk_engine(spec_decode=True, spec_tokens=4)
        got = [await collect(spec, mk_request(p, max_tokens=96))
               for p in prompts]
        stats = spec.stats()
        await spec.stop()
        for (t0, f0, _), (t1, f1, _) in zip(ref, got):
            assert t1 == t0 and f1 == f0 == "length"
        # the speculative arm actually ran (drafts were proposed)
        assert stats["spec_decode_steps"] > 0
        assert stats["spec_decode_draft_tokens_total"] > 0

    run_async(main())


def test_spec_acceptance_positive_on_repetitive_prompt(run_async):
    """On a repetitive workload the drafter's proposals survive the
    greedy verify: mean accepted draft length > 0, reported via
    stats() under the names the HTTP metrics plane scrapes."""

    async def main():
        spec = mk_engine(spec_decode=True, spec_tokens=4)
        for p in [(MOTIF * 6)[:72], list(range(10, 18))]:
            await collect(spec, mk_request(p, max_tokens=96))
        stats = spec.stats()
        await spec.stop()
        assert stats["spec_decode_accepted_tokens_total"] > 0
        assert stats["spec_decode_mean_accepted_len"] > 0
        assert 0 < stats["spec_decode_acceptance_rate"] <= 1

    run_async(main())


# ------------------------------------------------------------- the bypass


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_spec_bypass_for_sampled_penalty_logprobs(run_async):
    """Requests the greedy verify cannot reproduce — temperature
    sampling, count-state penalties, logprobs — bypass speculation
    entirely (no drafts attempted) yet still complete on the fallback
    path, and deterministic ones match the non-speculative engine."""

    async def main():
        prompt = (MOTIF * 6)[:72]
        reqs = dict(
            sampled=mk_request(prompt, max_tokens=16, temperature=0.8,
                               seed=7),
            penalized=mk_request(prompt, max_tokens=16,
                                 repetition_penalty=1.3),
            logprobs=mk_request(prompt, max_tokens=16, logprobs=3),
        )
        base = mk_engine()
        ref = {k: await collect(base, r) for k, r in reqs.items()}
        await base.stop()
        spec = mk_engine(spec_decode=True, spec_tokens=4)
        got = {k: await collect(spec, r) for k, r in reqs.items()}
        stats = spec.stats()
        await spec.stop()
        # nothing was drafted: every row bypassed the speculative arm
        assert stats["spec_decode_steps"] == 0
        assert stats["spec_decode_draft_tokens_total"] == 0
        for k in reqs:
            toks, fin, lps = got[k]
            assert len(toks) == 16 and fin == "length"
            assert toks == ref[k][0], k
        assert len(got["logprobs"][2]) == 16  # aux still flows

    run_async(main())


def test_spec_mixed_batch_spec_and_bypass_rows(run_async):
    """Spec rows and bypass rows coexist in one continuous batch: the
    scheduler partitions them per iteration (verify dispatch + fallback
    dispatch) without cross-talk."""

    async def main():
        spec = mk_engine(spec_decode=True, spec_tokens=4)
        reqs = [mk_request((MOTIF * 6)[:72], max_tokens=48),
                mk_request(list(range(30, 40)), max_tokens=24,
                           temperature=0.8, seed=7),
                mk_request(list(range(50, 60)), max_tokens=24, logprobs=3)]
        res = await asyncio.gather(*(collect(spec, r) for r in reqs))
        stats = spec.stats()
        await spec.stop()
        assert [len(t) for t, _, _ in res] == [48, 24, 24]
        assert all(f == "length" for _, f, _ in res)
        assert len(res[2][2]) == 24          # logprobs on the bypass row
        assert stats["spec_decode_steps"] > 0  # spec row really ran spec
        assert stats["kv_active_blocks"] == 0

    run_async(main())


# ------------------------------------------------------- page accounting


def test_spec_page_accounting_after_partial_acceptance(run_async):
    """Partial accepts write junk KV past the accepted extent; the
    invariants that make that safe must hold observably: all pages
    release on finish, committed prefix pages stay reusable, and a
    cache-hit rerun reproduces the identical stream."""

    async def main():
        spec = mk_engine(spec_decode=True, spec_tokens=4, page_size=8)
        prompt = (MOTIF * 6)[:72]
        t1, f1, _ = await collect(spec, mk_request(prompt, max_tokens=40))
        st1 = spec.stats()
        assert st1["kv_active_blocks"] == 0  # everything released
        # rerun: prefix cache serves the prompt, stream is identical —
        # junk KV from rejected drafts never reached a published page
        t2, f2, _ = await collect(spec, mk_request(prompt, max_tokens=40))
        st2 = spec.stats()
        await spec.stop()
        assert (t2, f2) == (t1, f1)
        assert spec.prefix_hit_tokens_total > 0
        assert st2["kv_active_blocks"] == 0
        assert spec.pm.available == len(spec.pm.free) + len(spec.pm.reusable)

    run_async(main())


def test_spec_flag_off_leaves_engine_untouched(run_async):
    """With spec_decode off (the default) no verify fn is built and the
    spec counters stay zero — the compiled-program set is the standard
    grid."""

    async def main():
        eng = mk_engine()
        assert eng.verify_fn is None
        toks, fin, _ = await collect(eng, mk_request(MOTIF * 3,
                                                     max_tokens=8))
        stats = eng.stats()
        await eng.stop()
        assert len(toks) == 8 and fin == "length"
        assert stats["spec_decode_steps"] == 0
        assert stats["spec_decode_draft_tokens_total"] == 0

    run_async(main())


def test_spec_respects_max_tokens_near_budget(run_async):
    """A draft is clamped so a full accept can never overshoot
    max_tokens: rows close to their budget emit exactly max_tokens."""

    async def main():
        spec = mk_engine(spec_decode=True, spec_tokens=4)
        for mt in (1, 2, 3, 5):
            toks, fin, _ = await collect(
                spec, mk_request((MOTIF * 6)[:72], max_tokens=mt))
            assert len(toks) == mt and fin == "length"
        stats = spec.stats()
        await spec.stop()
        assert stats["kv_active_blocks"] == 0

    run_async(main())
