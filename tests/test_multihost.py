"""Two-process multi-host SPMD bootstrap (VERDICT r4 task 6): proves
``initialize_multihost`` — the replacement for the reference's Ray
bootstrap (lib/llm/src/engines/vllm/ray.rs) — actually executes:
2 OS processes × 2 virtual CPU devices each join one jax.distributed
group, build the global 2x2 data×model mesh, and run a sharded forward
whose shards match a local oracle (tests/multihost_worker.py)."""

import os
import socket
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_spmd_forward():
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=2"])
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, WORKER, coord, "2", str(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()  # SIGTERM only (relay discipline)
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\n{out}\n{err[-3000:]}"
        assert "MULTIHOST-OK" in out, out
        assert "procs=2" in out and "global_devices=4" in out, out
