"""DCP durability: snapshot + journal recovery (the single-process
answer to the reference's raft-replicated etcd + JetStream persistence,
reference deploy/docker-compose.yml:16-31)."""

import asyncio
import os
import signal
import subprocess
import sys
import time

from dynamo_tpu.runtime.dcp_client import DcpClient
from dynamo_tpu.runtime.dcp_server import DcpServer


def test_restart_recovers_kv_and_queues(run_async, tmp_path):
    jpath = str(tmp_path / "dcp")

    async def main():
        s1 = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s1.address)
        await c.kv_put("models/a", b"spec-a")
        await c.kv_put("models/b", b"spec-b")
        rev_b = (await c.kv_get_item("models/b")).mod_rev
        await c.kv_put("models/a", b"spec-a2")      # overwrite
        await c.kv_delete("models/b")
        # leased key: ephemeral, must NOT survive restart
        lease = await c.lease_grant(ttl=30)
        await c.kv_put("instances/w1", b"alive", lease=lease)
        # queue: 3 in, 1 out -> 2 must survive in order
        for i in range(3):
            await c.queue_put("ns.pq", b"item%d" % i)
        assert await c.queue_pull("ns.pq") == b"item0"
        await c.close()
        # simulate crash: no graceful stop()/snapshot — close the
        # listener only and recover purely from the journal
        s1._journal.close()
        s1._journal = None
        await s1.stop()

        s2 = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s2.address)
        assert await c.kv_get("models/a") == b"spec-a2"
        assert await c.kv_get("models/b") is None
        assert await c.kv_get("instances/w1") is None   # lease died
        assert await c.queue_len("ns.pq") == 2
        assert await c.queue_pull("ns.pq") == b"item1"
        assert await c.queue_pull("ns.pq") == b"item2"
        # revision counter is monotone across restart so CAS tokens from
        # before the crash cannot alias a post-restart write
        item = await c.kv_get_item("models/a")
        assert item.mod_rev > rev_b
        await c.kv_put("models/c", b"x")
        assert (await c.kv_get_item("models/c")).mod_rev > item.mod_rev
        await c.close()
        await s2.stop()

    run_async(main())


def test_rev_monotone_past_leased_puts(run_async, tmp_path):
    """Leased puts bump the revision counter without being durable; the
    counter itself must still recover, or a CAS token captured before
    the crash could alias (and silently overwrite) a post-restart
    write."""
    jpath = str(tmp_path / "dcp")

    async def main():
        s1 = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s1.address)
        await c.kv_put("durable/x", b"v")           # journaled, rev=1
        lease = await c.lease_grant(ttl=30)
        await c.kv_put("inst/w", b"alive", lease=lease)   # rev=2, leased
        stale_rev = (await c.kv_get_item("inst/w")).mod_rev
        await c.close()
        s1._journal.close()
        s1._journal = None
        await s1.stop()

        s2 = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s2.address)
        await c.kv_put("inst/w", b"new-durable")
        new_rev = (await c.kv_get_item("inst/w")).mod_rev
        assert new_rev > stale_rev
        # the pre-crash token must not be able to CAS over the new value
        assert await c.kv_cas("inst/w", b"stale-write", stale_rev) is False
        assert await c.kv_get("inst/w") == b"new-durable"
        await c.close()
        await s2.stop()

    run_async(main())


def test_compaction_preserves_state(run_async, tmp_path):
    jpath = str(tmp_path / "dcp")

    async def main():
        s1 = await DcpServer.start(journal_path=jpath)
        s1._journal.max_log_bytes = 512   # force compaction quickly
        c = await DcpClient.connect(s1.address)
        for i in range(50):
            await c.kv_put("k/%02d" % (i % 10), b"v%d" % i)
        await c.queue_put("q", b"survivor")
        await c.close()
        assert os.path.exists(jpath + ".snap"), "compaction never ran"
        assert s1._journal.log_size < 512
        s1._journal.close()
        s1._journal = None   # crash: skip the graceful-stop snapshot
        await s1.stop()

        s2 = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s2.address)
        for i in range(40, 50):
            assert await c.kv_get("k/%02d" % (i % 10)) == b"v%d" % i
        assert await c.queue_pull("q") == b"survivor"
        await c.close()
        await s2.stop()

    run_async(main())


def test_sigkill_mid_serving_restart(run_async, tmp_path):
    """The VERDICT scenario: kill -9 the DCP process mid-serving, restart
    it on the same journal, and find every durable write still there."""
    jpath = str(tmp_path / "dcp")
    port = 16711

    def spawn():
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.runtime.dcp_server",
             "--port", str(port), "--journal", jpath],
            cwd=os.path.join(os.path.dirname(__file__), ".."),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        deadline = time.time() + 20
        while time.time() < deadline:
            line = proc.stdout.readline().decode()
            if "listening" in line:
                return proc
        raise RuntimeError("dcp server did not start")

    async def write_phase():
        c = await DcpClient.connect(f"127.0.0.1:{port}")
        for i in range(20):
            await c.kv_put("dep/%d" % i, b"spec%d" % i)
        for i in range(5):
            await c.queue_put("ns.prefill", b"req%d" % i)
        await c.close()

    async def read_phase():
        c = await DcpClient.connect(f"127.0.0.1:{port}")
        for i in range(20):
            assert await c.kv_get("dep/%d" % i) == b"spec%d" % i
        assert await c.queue_len("ns.prefill") == 5
        await c.close()

    proc = spawn()
    try:
        run_async(write_phase())
        proc.kill()                    # SIGKILL: no snapshot, no cleanup
        proc.wait(timeout=10)
        proc = spawn()                 # same journal
        run_async(read_phase())
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_torn_tail_write_dropped(run_async, tmp_path):
    """A partial final record (crash mid-append) is discarded; everything
    before it recovers."""
    jpath = str(tmp_path / "dcp")

    async def phase1():
        s = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s.address)
        await c.kv_put("a", b"1")
        await c.kv_put("b", b"2")
        await c.close()
        s._journal.close()
        s._journal = None
        await s.stop()

    async def phase2():
        s = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s.address)
        assert await c.kv_get("a") == b"1"
        assert await c.kv_get("b") == b"2"
        assert await c.kv_get("c") is None
        await c.close()
        await s.stop()

    run_async(phase1())
    # simulate a torn write: append a length header promising more bytes
    # than exist
    with open(jpath + ".log", "ab") as f:
        f.write((1000).to_bytes(4, "big") + b"partial")
    run_async(phase2())


def test_torn_tail_then_new_writes_then_crash(run_async, tmp_path):
    """open() must truncate the torn tail on disk — otherwise records
    appended after the garbage are unreachable on the NEXT recovery."""
    jpath = str(tmp_path / "dcp")

    async def phase(write_key, expect):
        s = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s.address)
        if write_key:
            await c.kv_put(write_key, b"v-" + write_key.encode())
        for k in expect:
            assert await c.kv_get(k) == b"v-" + k.encode(), k
        await c.close()
        s._journal.close()
        s._journal = None      # crash: no graceful snapshot
        await s.stop()

    run_async(phase("a", ["a"]))
    with open(jpath + ".log", "ab") as f:
        f.write((999).to_bytes(4, "big") + b"torn")
    run_async(phase("b", ["a", "b"]))      # recovers past tail, writes b
    run_async(phase(None, ["a", "b"]))     # b survives the second crash


def test_crash_between_snapshot_and_truncate(run_async, tmp_path):
    """The compaction crash window: new snapshot renamed in, old log not
    yet truncated. Replay must seq-skip the already-snapshotted records —
    a re-applied qput would double-deliver a prefill request."""
    jpath = str(tmp_path / "dcp")

    async def phase1():
        s = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s.address)
        await c.kv_put("x", b"1")
        await c.queue_put("q", b"only-once")
        # snapshot with the log intact = the mid-compaction crash state
        with open(jpath + ".log", "rb") as f:
            log_bytes = f.read()
        s._journal.snapshot(s._rev, s._durable_kv(), s._queues)
        with open(jpath + ".log", "wb") as f:
            f.write(log_bytes)           # "truncate never happened"
        await c.close()
        s._journal.close()
        s._journal = None
        await s.stop()

    async def phase2():
        s = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s.address)
        assert await c.kv_get("x") == b"1"
        assert await c.queue_len("q") == 1, "qput double-applied"
        assert await c.queue_pull("q") == b"only-once"
        await c.close()
        await s.stop()

    run_async(phase1())
    run_async(phase2())


def test_dcp_planes_roundtrip_under_wire_validation(run_async, monkeypatch,
                                                    tmp_path):
    """DYN_WIRE_VALIDATE=1 over a live DCP plane: watch pushes, pub/sub
    and request/reply deliveries all pass the runtime/wire.py registry
    check (the declared dcp.push_* / envelope schemas match real
    traffic), and survive a journaled restart."""
    monkeypatch.setenv("DYN_WIRE_VALIDATE", "1")
    jpath = str(tmp_path / "dcp")

    async def main():
        s = await DcpServer.start(journal_path=jpath)
        c = await DcpClient.connect(s.address)
        # watch pushes (dcp.push_watch): put + delete events validate
        items, watch = await c.kv_watch_prefix("models/")
        assert items == []
        await c.kv_put("models/a", b"spec")
        ev = await asyncio.wait_for(watch.__anext__(), 5)
        assert (ev.event, ev.key, ev.value) == ("put", "models/a", b"spec")
        await c.kv_delete("models/a")
        ev = await asyncio.wait_for(watch.__anext__(), 5)
        assert (ev.event, ev.value) == ("delete", None)
        await watch.stop()
        # pub/sub (dcp.push_msg) and request/reply (dcp.push_req)
        got = asyncio.Queue()

        async def on_msg(msg):
            if msg.needs_reply:
                await msg.respond(b"pong:" + msg.payload)
            else:
                got.put_nowait(msg.payload)

        await c.subscribe("plane.events", on_msg)
        await c.subscribe("plane.rpc", on_msg, group="workers")
        await c.publish("plane.events", b"hello")
        assert await asyncio.wait_for(got.get(), 5) == b"hello"
        assert await c.request("plane.rpc", b"ping", timeout=5) == b"pong:ping"
        # queue plane round-trip, validated and journaled
        await c.queue_put("ns.pq", b"job")
        await c.close()
        await s.stop()

        s2 = await DcpServer.start(journal_path=jpath)
        c2 = await DcpClient.connect(s2.address)
        assert await c2.queue_pull("ns.pq") == b"job"
        await c2.close()
        await s2.stop()

    run_async(main())
