"""dyntrace: span recorder, context/wire propagation, sampling no-op,
HTTP trace endpoints, and the end-to-end disagg trace (one trace_id
spanning frontend → route → prefill → kv_transfer stages → decode)."""

import asyncio
import json

import msgpack
import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.disagg import DisaggRouter, PrefillWorker
from dynamo_tpu.llm.disagg.decode import build_disagg_decode
from dynamo_tpu.llm.disagg.protocols import RemotePrefillRequest
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import init_params
from dynamo_tpu.runtime import codec, tracing
from dynamo_tpu.runtime.runtime import DistributedRuntime

PS = 8


@pytest.fixture(autouse=True)
def fresh_tracer():
    """Every test gets its own tracer (full sampling, small ring)."""
    tracer = tracing.configure(sample=1.0, ring=4096)
    yield tracer
    tracing.configure(sample=1.0, ring=4096)


def tiny_cfg():
    return ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=8,
                            hidden_size=32, vocab_size=128)


def make_engine(params=None):
    ecfg = EngineConfig(page_size=PS, num_pages=64, max_batch=4,
                        prefill_chunk=32, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8, 32), page_buckets=(8,),
                        watermark_pages=2)
    return JaxEngine(tiny_cfg(), ecfg, params=params)


# ------------------------------------------------------------- tracer core


def test_span_tree_and_ring(fresh_tracer):
    t = fresh_tracer
    with t.start_span("root", request_id="r1") as root:
        with t.start_span("child") as child:
            child.set_attribute("k", 1)
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
    spans = t.snapshot()
    assert [s.name for s in spans] == ["child", "root"]  # end order
    assert all(s.end_time is not None for s in spans)
    # request join table + stage rollup
    tr = t.get_request_trace("r1")
    assert tr is not None and tr["trace_id"] == root.trace_id
    assert {s["name"] for s in tr["spans"]} == {"root", "child"}
    assert set(tr["stages"]) == {"root", "child"}


def test_wire_ctx_parenting(fresh_tracer):
    t = fresh_tracer
    with t.start_span("upstream") as up:
        ctx = t.current_trace_ctx()
    assert ctx == {"trace_id": up.trace_id, "span_id": up.span_id}
    # a span started from the wire dict (other process) joins the trace
    with t.start_span("downstream", parent=ctx) as down:
        assert down.trace_id == up.trace_id
        assert down.parent_id == up.span_id


def test_record_span_synthesizes_duration(fresh_tracer):
    t = fresh_tracer
    with t.start_span("parent") as p:
        t.record_span("stage", 0.25, parent=p, attributes={"x": 1})
    stage = [s for s in t.snapshot() if s.name == "stage"][0]
    assert stage.parent_id == p.span_id
    assert 0.2 < stage.duration_s < 0.3


def test_sampling_zero_is_total_noop():
    t = tracing.configure(sample=0.0)
    with t.start_span("root", request_id="r") as root:
        assert not root.recording
        # no propagation → wire envelopes gain NO field
        assert t.current_trace_ctx() is None
        with t.start_span("child") as child:
            assert not child.recording
    assert t.spans_recorded == 0
    assert t.snapshot() == []
    assert t.get_request_trace("r") is None
    # queue protocol: absent trace_ctx = absent key (no envelope growth)
    req = RemotePrefillRequest(request_id="r", token_ids=[1],
                               trace_ctx=t.current_trace_ctx())
    assert "trace_ctx" not in req.to_dict()


def test_unsampled_root_suppresses_descendants():
    t = tracing.configure(sample=0.0)
    with t.start_span("root"):
        # even if sampling were re-enabled, a noop ambient parent wins
        t.sample = 1.0
        with t.start_span("child") as child:
            assert not child.recording
    assert t.spans_recorded == 0


def test_ring_is_bounded():
    t = tracing.configure(sample=1.0, ring=8)
    for i in range(50):
        with t.start_span(f"s{i}"):
            pass
    assert len(t.snapshot()) == 8
    assert t.spans_recorded == 50


def test_jsonl_export(tmp_path):
    path = tmp_path / "trace.jsonl"
    t = tracing.configure(sample=1.0, jsonl=str(path))
    with t.start_span("op", request_id="rx") as sp:
        sp.set_attribute("n", 3)
    lines = [json.loads(line) for line in
             path.read_text().strip().splitlines()]
    assert len(lines) == 1
    rec = lines[0]
    assert rec["name"] == "op" and rec["trace_id"] == sp.trace_id
    assert rec["attributes"] == {"request_id": "rx", "n": 3}
    assert rec["duration_ms"] is not None


def test_traceparent_roundtrip(fresh_tracer):
    with fresh_tracer.start_span("root") as sp:
        hdr = tracing.format_traceparent(sp)
    ctx = tracing.parse_traceparent(hdr)
    assert ctx == {"trace_id": sp.trace_id, "span_id": sp.span_id}
    # malformed / unsampled headers are rejected
    assert tracing.parse_traceparent(None) is None
    assert tracing.parse_traceparent("not-a-header") is None
    assert tracing.parse_traceparent(
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01") is None
    assert tracing.parse_traceparent(
        "00-" + "a" * 32 + "-" + "1" * 16 + "-00") is None  # not sampled


# --------------------------------------------------- envelope wire compat


def test_codec_roundtrip_with_and_without_trace_ctx():
    """The two-part frame and msgpack envelope carry the trace field
    transparently; peers without it still interoperate (absent = None)."""
    ctx = {"trace_id": "a" * 32, "span_id": "b" * 16}
    chunk = {"kind": "chunk", "request_id": "r", "chunk_idx": 0,
             "n_chunks": 1, "page_ids": [1], "shape": [1], "dtype": "f",
             "k_len": 1}  # full registered frame: DYN_WIRE_VALIDATE-safe
    with_trace = codec.encode(codec.TwoPartMessage(
        {**chunk, "trace": ctx}, b"kv"))
    without = codec.encode(codec.TwoPartMessage(dict(chunk), b"kv"))
    msg1, rest1 = codec.decode_buffer(with_trace)
    msg2, rest2 = codec.decode_buffer(without)
    assert rest1 == b"" and rest2 == b""
    assert msg1.header["trace"] == ctx and msg1.body == b"kv"
    assert msg2.header.get("trace") is None  # old peer: no parent
    # DCP request envelope (component.Client.generate shape)
    env = {"req_id": "r", "conn": {"address": "h:1", "subject": "s"},
           "payload": b"p"}
    assert msgpack.unpackb(msgpack.packb(env, use_bin_type=True),
                           raw=False).get("trace") is None
    env["trace"] = ctx
    assert msgpack.unpackb(msgpack.packb(env, use_bin_type=True),
                           raw=False)["trace"] == ctx


# ------------------------------------------------------- end-to-end disagg


def _greedy_chat_body(stream=False):
    return {"model": "m", "stream": stream, "max_tokens": 6,
            "temperature": 0.0,
            "messages": [{"role": "user", "content": "hi there"}]}


async def _build_disagg_http(params, drt):
    """HTTP frontend → LocalChatChain → DisaggDecodeEngine (+ remote
    prefill worker), all in-process over real DCP/TCP planes."""
    from dynamo_tpu.llm.engines import LocalChatChain
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    decode_eng = make_engine(params)
    prefill_eng = make_engine(params)
    router = DisaggRouter(max_local_prefill_length=4)  # force remote
    disagg = await build_disagg_decode(drt, decode_eng, namespace="trace",
                                       router=router, watch_config=False)
    pw = PrefillWorker(drt, prefill_eng, namespace="trace")
    pw.start()
    mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                              context_length=256)
    service = HttpService()
    service.manager.add_chat_model("m", LocalChatChain(mdc, disagg))
    await service.start(host="127.0.0.1", port=0)
    return service, disagg, pw, decode_eng, prefill_eng


async def _teardown(service, disagg, pw, decode_eng, prefill_eng):
    await service.stop()
    await pw.stop()
    await disagg.transfer.stop()
    await prefill_eng.stop()
    await decode_eng.stop()


def test_disagg_trace_end_to_end(run_async):
    """One chat completion through the remote-prefill path yields ONE
    trace covering http → route → prefill → kv_transfer stages → decode,
    with consistent trace_id across the queue/transfer envelopes, all
    retrievable from /v1/traces/{request_id}."""

    async def main():
        import aiohttp
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(5))
        drt = await DistributedRuntime.detached()
        handles = await _build_disagg_http(params, drt)
        service, disagg, pw = handles[0], handles[1], handles[2]
        base = f"http://127.0.0.1:{service.port}"
        rid = "trace-e2e-1"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(f"{base}/v1/chat/completions",
                                     json=_greedy_chat_body(),
                                     headers={"X-Request-Id": rid}) as r:
                    assert r.status == 200
                    # X-Request-Id echoed; traceparent emitted
                    assert r.headers["X-Request-Id"] == rid
                    assert "traceparent" in r.headers
                    full = await r.json()
                assert full["choices"][0]["message"]["content"] is not None
                assert disagg.remote_prefills == 1
                assert disagg.remote_fallbacks == 0

                async with http.get(f"{base}/v1/traces/{rid}") as r:
                    assert r.status == 200
                    tr = await r.json()
        finally:
            await _teardown(*handles)
            await drt.shutdown()

        spans = tr["spans"]
        names = {s["name"] for s in spans}
        # the full disagg request path in ONE trace
        for expected in ("http.request", "preprocess", "route.disagg",
                         "prefill.remote", "prefill.forward",
                         "kv_transfer.send", "kv_transfer.extract",
                         "kv_transfer.wire", "kv_transfer.inject", "decode"):
            assert expected in names, f"missing span {expected}: {names}"
        assert len({s["trace_id"] for s in spans}) == 1
        by_name = {s["name"]: s for s in spans}
        ids = {s["span_id"] for s in spans}
        # parent/child links: every non-root span's parent is in the trace
        root = by_name["http.request"]
        assert root["parent_id"] is None
        for s in spans:
            if s is not root:
                assert s["parent_id"] in ids, s
        # the cross-process hops hang off the decode-side request spans
        assert by_name["prefill.forward"]["parent_id"] == \
            by_name["prefill.remote"]["span_id"]
        assert by_name["kv_transfer.send"]["parent_id"] == \
            by_name["prefill.remote"]["span_id"]
        assert by_name["kv_transfer.inject"]["parent_id"] == \
            by_name["kv_transfer.send"]["span_id"]
        assert by_name["preprocess"]["parent_id"] == root["span_id"]
        # stage rollup is serviceable for a breakdown
        assert tr["stages"]["http.request"] >= tr["stages"]["decode"]

    run_async(main())


def test_traces_listing_and_engine_timeline(run_async):
    """/v1/traces lists recent traces and exposes the engine step
    timeline (admit queue-wait, prefill/decode dispatches)."""

    async def main():
        import aiohttp
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(6))
        drt = await DistributedRuntime.detached()
        handles = await _build_disagg_http(params, drt)
        service = handles[0]
        base = f"http://127.0.0.1:{service.port}"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(f"{base}/v1/chat/completions",
                                     json=_greedy_chat_body(),
                                     headers={"X-Request-Id": "list-1"}) as r:
                    assert r.status == 200
                    await r.json()
                async with http.get(f"{base}/v1/traces") as r:
                    assert r.status == 200
                    listing = await r.json()
                # unknown request id → 404 with the id echoed
                async with http.get(f"{base}/v1/traces/nope") as r:
                    assert r.status == 404
                # ITL + stage histograms in the exposition
                async with http.get(f"{base}/metrics") as r:
                    metrics = await r.text()
        finally:
            await _teardown(*handles)
            await drt.shutdown()

        assert any(t["request_id"] == "list-1" for t in listing["traces"])
        # both engines registered a step timeline; events carry the fields
        timelines = listing["engine_steps"]
        assert timelines, "no engine step timelines registered"
        events = [e for tl in timelines.values() for e in tl]
        kinds = {e["kind"] for e in events}
        assert "admit" in kinds and "prefill" in kinds
        admits = [e for e in events if e["kind"] == "admit"]
        assert all("queue_wait_ms" in e and "occupancy" in e
                   for e in admits)
        assert "dyn_llm_http_service_stage_duration_seconds_bucket" in metrics
        assert 'stage="prefill.remote"' in metrics

    run_async(main())


def test_sampling_zero_end_to_end(run_async):
    """DYN_TRACE_SAMPLE=0: the full disagg path serves identically with
    zero spans recorded and zero trace fields on any envelope."""

    async def main():
        import aiohttp
        import jax

        tracer = tracing.configure(sample=0.0)
        params = init_params(tiny_cfg(), jax.random.PRNGKey(7))
        drt = await DistributedRuntime.detached()
        handles = await _build_disagg_http(params, drt)
        service, disagg = handles[0], handles[1]
        base = f"http://127.0.0.1:{service.port}"
        rid = "unsampled-1"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(f"{base}/v1/chat/completions",
                                     json=_greedy_chat_body(stream=True),
                                     headers={"X-Request-Id": rid}) as r:
                    assert r.status == 200
                    # the request id still echoes on the SSE response...
                    assert r.headers["X-Request-Id"] == rid
                    # ...but no traceparent: nothing was sampled
                    assert "traceparent" not in r.headers
                    async for line in r.content:
                        if line.decode().strip() == "data: [DONE]":
                            break
                assert disagg.remote_prefills == 1
                # no SPANS at sample=0 — but dynaprof cost attribution is
                # always-on, so /v1/traces/{rid} serves a cost-only
                # payload with an empty span list instead of a 404
                async with http.get(f"{base}/v1/traces/{rid}") as r:
                    assert r.status == 200
                    body = await r.json()
                    assert body["spans"] == []
                    assert body["cost"]["decode_tokens"] >= 1
                async with http.get(f"{base}/v1/traces/never-seen") as r:
                    assert r.status == 404
        finally:
            await _teardown(*handles)
            await drt.shutdown()

        # zero overhead: no span ever touched the ring
        assert tracer.spans_recorded == 0
        assert tracer.snapshot() == []

    run_async(main())


def test_itl_recorded_for_streams(run_async):
    """Streaming responses feed the ITL histogram next to TTFT."""

    async def main():
        import aiohttp

        from dynamo_tpu.engine.echo import EchoEngineCore
        from dynamo_tpu.llm.engines import LocalChatChain
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.model_card import ModelDeploymentCard

        mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                                  context_length=256)
        service = HttpService()
        service.manager.add_chat_model(
            "m", LocalChatChain(mdc, EchoEngineCore(delay_ms=0)))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(f"{base}/v1/chat/completions",
                                     json=_greedy_chat_body(stream=True)) as r:
                    assert r.status == 200
                    async for line in r.content:
                        if line.decode().strip() == "data: [DONE]":
                            break
                async with http.get(f"{base}/metrics") as r:
                    metrics = await r.text()
        finally:
            await service.stop()

        assert "# TYPE dyn_llm_http_service_itl_seconds histogram" in metrics
        assert 'dyn_llm_http_service_itl_seconds_count{model="m"}' in metrics
        assert 'dyn_llm_http_service_time_to_first_token_seconds_count' \
            in metrics

    run_async(main())


def test_request_id_logging_filter():
    """Log records carry the bound request id (JSONL joinable with
    traces), independent of sampling."""
    import logging as _logging

    from dynamo_tpu.runtime.logging import JsonlFormatter, RequestIdFilter

    tracing.configure(sample=0.0)  # sampling off: logs still join
    tracing.bind_request_id("log-join-1")
    rec = _logging.LogRecord("dynamo_tpu.test", _logging.INFO, __file__, 1,
                             "served", None, None)
    assert RequestIdFilter().filter(rec)
    out = json.loads(JsonlFormatter().format(rec))
    assert out["request_id"] == "log-join-1"
    tracing.bind_request_id(None)


def test_prefill_queue_carries_trace_ctx(run_async):
    """RemotePrefillRequest round-trips trace_ctx over the real queue;
    absent field stays absent."""

    async def main():
        from dynamo_tpu.llm.disagg import PrefillQueue

        drt = await DistributedRuntime.detached()
        try:
            q = PrefillQueue(drt.dcp, "tq")
            ctx = {"trace_id": "c" * 32, "span_id": "d" * 16}
            await q.put(RemotePrefillRequest(request_id="a", token_ids=[1],
                                             trace_ctx=ctx))
            await q.put(RemotePrefillRequest(request_id="b", token_ids=[2]))
            got_a = await q.pull(timeout=1.0)
            got_b = await q.pull(timeout=1.0)
            assert got_a.trace_ctx == ctx
            assert got_b.trace_ctx is None
        finally:
            await drt.shutdown()

    run_async(main())
