"""LLM-layer tests: tokenizer + incremental detok, preprocessor lowering,
backend stop handling, and the HTTP frontend end-to-end with echo engines
(reference test model: lib/llm/tests/http-service.rs + preprocessor.rs)."""

import asyncio
import json

import pytest

from dynamo_tpu.engine.echo import EchoEngineCore
from dynamo_tpu.llm.backend import Backend, StopSequenceJail
from dynamo_tpu.llm.engines import LocalChatChain
from dynamo_tpu.llm.entry import ModelEntry, register_model, remove_model
from dynamo_tpu.llm.http.discovery import ModelWatcher
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.common import EngineOutput, PreprocessedRequest
from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest, ChatMessage
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.llm.worker import serve_openai_model
from dynamo_tpu.runtime import Context, DistributedRuntime


def make_mdc(**kw):
    return ModelDeploymentCard(name="test-model", tokenizer_kind="byte", **kw)


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello, TPU! ünïcödé")
    assert ids[0] == tok.BOS
    assert tok.decode(ids) == "hello, TPU! ünïcödé"


def test_decode_stream_utf8_safety():
    tok = ByteTokenizer()
    text = "héllo →🌍"
    ids = tok.encode(text, add_special_tokens=False)
    ds = tok.decode_stream()
    out = []
    for tid in ids:
        piece = ds.step(tid)
        assert "�" not in piece  # never emit partial codepoints
        out.append(piece)
    assert "".join(out) + ds.flush() == text


def test_stop_sequence_jail():
    jail = StopSequenceJail(["STOP"])
    text, hit = jail.feed("hello S")
    assert (text, hit) == ("hello ", False)  # 'S' held: could start STOP
    text, hit = jail.feed("T")
    assert (text, hit) == ("", False)  # 'ST' held
    text, hit = jail.feed("ban")  # 'STban' → not a stop prefix → release all
    assert (text, hit) == ("STban", False)
    text, hit = jail.feed("xx STOP yy")
    assert (text, hit) == ("xx ", True)  # truncate at stop


def test_preprocessor_chat_lowering():
    mdc = make_mdc(context_length=4096)
    pre = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest(
        model="test-model",
        messages=[ChatMessage(role="user", content="hi there")],
        max_tokens=32, temperature=0.5, stop=["\n\n"],
        ext={"annotations": ["formatted_prompt", "token_ids"]})
    out, annotations = pre.preprocess_chat(req)
    assert isinstance(out, PreprocessedRequest)
    prompt = pre.tokenizer.decode(out.token_ids)
    assert "hi there" in prompt and "<|user|>" in prompt
    assert "<|assistant|>" in prompt  # generation prompt appended
    assert out.stop.max_tokens == 32
    assert out.stop.stop == ["\n\n"]
    assert out.sampling.temperature == 0.5
    assert out.eos_token_ids == [ByteTokenizer.EOS]
    names = [a.event for a in annotations]
    assert names == ["formatted_prompt", "token_ids"]
    # round-trips through the wire format
    assert PreprocessedRequest.from_dict(out.to_dict()).token_ids == out.token_ids

    # context overflow rejected
    mdc_small = make_mdc(context_length=4)
    with pytest.raises(ValueError):
        OpenAIPreprocessor(mdc_small).preprocess_chat(req)


def test_backend_detokenizes_and_stops(run_async):
    """Echo engine returns prompt tokens; backend must emit text and stop at
    max_tokens with finish_reason=length."""

    async def main():
        mdc = make_mdc()
        pre = OpenAIPreprocessor(mdc)
        backend = Backend(EchoEngineCore(delay_ms=0), pre.tokenizer)
        req = ChatCompletionRequest(
            model="m", messages=[ChatMessage(role="user", content="abcdefgh")],
            max_tokens=5)
        lowered, _ = pre.preprocess_chat(req)
        outs = []
        async for out in backend.generate(lowered, Context()):
            outs.append(out)
        assert outs[-1].finish_reason == "length"
        assert outs[-1].completion_tokens == 5
        text = "".join(o.text or "" for o in outs)
        assert len(text) > 0

    run_async(main())


def test_backend_eos_stop(run_async):
    async def main():
        tok = ByteTokenizer()

        class EosEngine:
            async def generate(self, request, context):
                yield EngineOutput(token_ids=tok.encode("ok", False))
                yield EngineOutput(token_ids=[tok.EOS])
                yield EngineOutput(token_ids=tok.encode("NEVER", False))

        backend = Backend(EosEngine(), tok)
        req = PreprocessedRequest(token_ids=[1], eos_token_ids=[tok.EOS])
        outs = [o async for o in backend.generate(req, Context())]
        assert outs[-1].finish_reason == "eos"
        assert "NEVER" not in "".join(o.text or "" for o in outs)

    run_async(main())


def test_http_service_local_chain(run_async):
    """HTTP frontend with a local echo chain: SSE stream + [DONE], unary
    aggregation, /v1/models, /metrics counters."""

    async def main():
        import aiohttp

        mdc = make_mdc()
        service = HttpService()
        service.manager.add_chat_model(
            "test-model", LocalChatChain(mdc, EchoEngineCore(delay_ms=0)))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"

        async with aiohttp.ClientSession() as http:
            # /v1/models
            async with http.get(f"{base}/v1/models") as r:
                models = await r.json()
            assert [m["id"] for m in models["data"]] == ["test-model"]

            # streaming chat
            body = {"model": "test-model", "stream": True, "max_tokens": 8,
                    "stream_options": {"include_usage": True},
                    "messages": [{"role": "user", "content": "hello world"}]}
            chunks, done = [], False
            async with http.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        done = True
                        break
                    chunks.append(json.loads(payload))
            assert done
            assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
            text = "".join(c["choices"][0]["delta"].get("content") or ""
                           for c in chunks if c["choices"])
            assert len(text) > 0
            finals = [c for c in chunks
                      if c["choices"] and c["choices"][0].get("finish_reason")]
            assert finals and finals[-1]["choices"][0]["finish_reason"] == "length"
            usage = [c for c in chunks if c.get("usage")]
            assert usage and usage[-1]["usage"]["completion_tokens"] == 8

            # unary chat
            body2 = dict(body, stream=False)
            body2.pop("stream_options")
            async with http.post(f"{base}/v1/chat/completions", json=body2) as r:
                assert r.status == 200
                full = await r.json()
            assert full["object"] == "chat.completion"
            assert full["choices"][0]["message"]["content"]

            # unknown model -> 404
            async with http.post(f"{base}/v1/chat/completions",
                                 json=dict(body, model="nope")) as r:
                assert r.status == 404

            # malformed body -> 400
            async with http.post(f"{base}/v1/chat/completions",
                                 json={"model": "test-model"}) as r:
                assert r.status == 400

            # metrics
            async with http.get(f"{base}/metrics") as r:
                metrics = await r.text()
            assert 'requests_total{model="test-model"' in metrics
            assert 'status="success"' in metrics

        await service.stop()

    run_async(main())


def test_distributed_serving_with_discovery(run_async):
    """Full distributed slice: worker serves a model over the runtime and
    registers it; the frontend's ModelWatcher discovers it; HTTP requests
    stream end-to-end; worker withdrawal removes the model."""

    async def main():
        import aiohttp

        drt = await DistributedRuntime.detached()
        mdc = make_mdc()
        handle = await serve_openai_model(
            drt, mdc, EchoEngineCore(delay_ms=0), namespace="demo")

        service = HttpService()
        watcher = ModelWatcher(drt, service.manager)
        await watcher.start()
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"

        async with aiohttp.ClientSession() as http:
            async with http.get(f"{base}/v1/models") as r:
                models = await r.json()
            assert [m["id"] for m in models["data"]] == ["test-model"]

            body = {"model": "test-model", "stream": True, "max_tokens": 4,
                    "messages": [{"role": "user", "content": "distributed!"}]}
            saw_data = False
            async with http.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if line.startswith("data: ") and "[DONE]" not in line:
                        saw_data = True
                    if "[DONE]" in line:
                        break
            assert saw_data

            # model withdrawal via explicit remove (llmctl remove analog)
            await remove_model(drt.dcp, "test-model")
            await asyncio.sleep(0.2)
            async with http.get(f"{base}/v1/models") as r:
                models = await r.json()
            assert models["data"] == []

        await handle.stop()
        await watcher.stop()
        await service.stop()
        await drt.shutdown()

    run_async(main())


def test_backend_flushes_held_text_with_finish(run_async):
    """Regression: jail/decoder-held text must ride the finish-bearing chunk
    (consumers stop at the first finish_reason)."""

    async def main():
        tok = ByteTokenizer()

        class TailEngine:
            async def generate(self, request, context):
                # ends with 'S' — a proper prefix of the stop seq "STOP"
                yield EngineOutput(token_ids=tok.encode("abcS", False))

        backend = Backend(TailEngine(), tok)
        from dynamo_tpu.llm.protocols.common import StopConditions

        req = PreprocessedRequest(token_ids=[1], eos_token_ids=[tok.EOS],
                                  stop=StopConditions(max_tokens=4, stop=["STOP"]))
        outs = [o async for o in backend.generate(req, Context())]
        final = [o for o in outs if o.finish_reason]
        assert final and final[0].finish_reason == "length"
        assert "".join(o.text or "" for o in outs) == "abcS"  # tail released

    run_async(main())


def test_http_error_paths_and_annotations(run_async):
    """Regression: early stream errors → clean HTTP 400 (not a broken SSE
    stream); requested annotations surface as SSE events."""

    async def main():
        import aiohttp

        mdc = make_mdc(context_length=64)
        service = HttpService()
        service.manager.add_chat_model(
            "m", LocalChatChain(mdc, EchoEngineCore(delay_ms=0)))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"

        async with aiohttp.ClientSession() as http:
            # context overflow on a STREAMING request → 400 json error
            big = {"model": "m", "stream": True,
                   "messages": [{"role": "user", "content": "x" * 500}]}
            async with http.post(f"{base}/v1/chat/completions", json=big) as r:
                assert r.status == 400
                err = await r.json()
                assert "context" in err["error"]["message"]

            # max_tokens=0 → 400
            bad = {"model": "m", "max_tokens": 0,
                   "messages": [{"role": "user", "content": "hi"}]}
            async with http.post(f"{base}/v1/chat/completions", json=bad) as r:
                assert r.status == 400

            # annotations surface as SSE events
            body = {"model": "m", "stream": True, "max_tokens": 2,
                    "ext": {"annotations": ["formatted_prompt"]},
                    "messages": [{"role": "user", "content": "hi"}]}
            events = []
            async with http.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if line.startswith("event: "):
                        events.append(line[len("event: "):])
                    if "[DONE]" in line:
                        break
            assert "formatted_prompt" in events

        await service.stop()

    run_async(main())


def test_http_n_choices(run_async):
    """OpenAI n>1 (accepted-but-ignored until r5): unary responses carry
    n distinct-index choices with summed usage; streaming chunks carry
    per-choice indices and ONE [DONE]. Runs over the echo chain (the
    reference inherits n from vLLM SamplingParams; here it fans out
    n single-choice pipeline passes — tests/test_penalties.py covers the
    real engine's seed derivation)."""

    async def main():
        import aiohttp

        mdc = make_mdc()
        service = HttpService()
        service.manager.add_chat_model(
            "m", LocalChatChain(mdc, EchoEngineCore(delay_ms=0)))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"

        async with aiohttp.ClientSession() as http:
            body = {"model": "m", "max_tokens": 6, "n": 3,
                    "messages": [{"role": "user", "content": "abc"}]}
            async with http.post(f"{base}/v1/chat/completions",
                                 json=body) as r:
                assert r.status == 200, await r.text()
                full = await r.json()
            choices = full["choices"]
            assert [c["index"] for c in choices] == [0, 1, 2]
            assert all(c["message"]["content"] for c in choices)
            assert all(c["finish_reason"] == "length" for c in choices)

            sbody = dict(body, stream=True,
                         stream_options={"include_usage": True})
            seen_idx = set()
            done_count = 0
            usages = []
            ids = set()
            async with http.post(f"{base}/v1/chat/completions",
                                 json=sbody) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        done_count += 1
                        continue
                    c = json.loads(payload)
                    if c.get("id"):
                        ids.add(c["id"])
                    for ch in c.get("choices", []):
                        seen_idx.add(ch["index"])
                    if c.get("usage"):
                        usages.append(c["usage"])
            assert done_count == 1
            assert seen_idx == {0, 1, 2}
            # OpenAI stream semantics: ONE id across all chunks, and
            # exactly ONE (merged) usage chunk — per-child usage never
            # leaks through
            assert len(ids) == 1, ids
            assert len(usages) == 1
            assert usages[0]["completion_tokens"] == 18  # 3 x 6
        await service.stop()

    run_async(main())


def test_completions_echo(run_async):
    """OpenAI completions echo=true: the response text starts with the
    prompt (accepted-but-ignored until r5)."""

    async def main():
        import aiohttp

        mdc = make_mdc()
        service = HttpService()
        from dynamo_tpu.llm.engines import LocalCompletionChain
        service.manager.add_completions_model(
            "m", LocalCompletionChain(mdc, EchoEngineCore(delay_ms=0)))
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            body = {"model": "m", "prompt": "hello", "max_tokens": 4,
                    "echo": True}
            async with http.post(f"{base}/v1/completions", json=body) as r:
                assert r.status == 200, await r.text()
                full = await r.json()
            text = full["choices"][0]["text"]
            assert text.startswith("hello"), text
            assert len(text) > len("hello")
            # echo off: no prompt prefix
            async with http.post(f"{base}/v1/completions",
                                 json=dict(body, echo=False)) as r:
                plain = await r.json()
            assert not plain["choices"][0]["text"].startswith("hello")
        await service.stop()

    run_async(main())
