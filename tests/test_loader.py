"""Checkpoint loader round-trips: synthetic HF-named safetensors built by
inverting the load mapping must come back equal to the source params
(Llama, Qwen2 bias, Mixtral MoE, DeepSeek MLA)."""

import json
import os

import jax
import numpy as np
import pytest
from safetensors.numpy import save_file

from dynamo_tpu.models import llama, mla
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.loader import load_params


def _write_ckpt(tmp_path, tensors, cfg_dict):
    save_file({k: np.ascontiguousarray(np.asarray(v))
               for k, v in tensors.items()},
              os.path.join(tmp_path, "model.safetensors"))
    with open(os.path.join(tmp_path, "config.json"), "w") as f:
        json.dump(cfg_dict, f)


def _hf_common(p, cfg, t):
    t["model.embed_tokens.weight"] = p["embed"]
    t["model.norm.weight"] = p["ln_final"]
    if "lm_head" in p:
        t["lm_head.weight"] = np.asarray(p["lm_head"]).T
    for i in range(cfg.num_layers):
        t[f"model.layers.{i}.input_layernorm.weight"] = p["ln_attn"][i]
        t[f"model.layers.{i}.post_attention_layernorm.weight"] = \
            p["ln_mlp"][i]


def _hf_dense_mlp(p, cfg, t):
    for i in range(cfg.num_layers):
        t[f"model.layers.{i}.mlp.gate_proj.weight"] = \
            np.asarray(p["w_gate"][i]).T
        t[f"model.layers.{i}.mlp.up_proj.weight"] = np.asarray(p["w_up"][i]).T
        t[f"model.layers.{i}.mlp.down_proj.weight"] = \
            np.asarray(p["w_down"][i]).T


def _assert_tree_close(a, b):
    assert set(a) == set(b), (set(a) ^ set(b))
    for k in a:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=1e-6, atol=1e-6, err_msg=k)


def test_loader_llama_qwen_bias_roundtrip(tmp_path):
    cfg = ModelConfig.tiny(attn_bias=True, tie_word_embeddings=False)
    p = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=np.float32)
    t = {}
    _hf_common(p, cfg, t)
    _hf_dense_mlp(p, cfg, t)
    for i in range(cfg.num_layers):
        for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"),
                         ("wv", "v_proj"), ("wo", "o_proj")):
            t[f"model.layers.{i}.self_attn.{hf}.weight"] = \
                np.asarray(p[ours][i]).T
        for ours, hf in (("bq", "q_proj"), ("bk", "k_proj"),
                         ("bv", "v_proj")):
            t[f"model.layers.{i}.self_attn.{hf}.bias"] = p[ours][i]
    _write_ckpt(str(tmp_path), t, {
        "model_type": "qwen2", "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim, "rope_theta": cfg.rope_theta})
    loaded = load_params(str(tmp_path), dtype=np.float32)
    _assert_tree_close(loaded, p)


def test_loader_mla_roundtrip(tmp_path):
    cfg = ModelConfig(model_type="deepseek_v2", vocab_size=256,
                      hidden_size=32, intermediate_size=64, num_layers=2,
                      num_heads=2, num_kv_heads=2, kv_lora_rank=8,
                      qk_nope_head_dim=8, qk_rope_head_dim=4, v_head_dim=8,
                      q_lora_rank=12, dtype="float32",
                      tie_word_embeddings=False)
    p = mla.init_params(cfg, jax.random.PRNGKey(1), dtype=np.float32)
    H, r = cfg.num_heads, cfg.kv_lora_rank
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    t = {}
    _hf_common(p, cfg, t)
    _hf_dense_mlp(p, cfg, t)
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}.self_attn"
        t[f"{pre}.kv_a_proj_with_mqa.weight"] = np.asarray(p["w_dkv"][i]).T
        t[f"{pre}.kv_a_layernorm.weight"] = p["kv_norm"][i]
        uk = np.asarray(p["w_uk"][i]).reshape(r, H, dn)
        uv = np.asarray(p["w_uv"][i]).reshape(r, H, dv)
        kvb = np.concatenate([uk, uv], axis=-1).reshape(r, H * (dn + dv))
        t[f"{pre}.kv_b_proj.weight"] = kvb.T
        t[f"{pre}.o_proj.weight"] = np.asarray(p["w_o"][i]).T
        t[f"{pre}.q_a_proj.weight"] = np.asarray(p["w_dq"][i]).T
        t[f"{pre}.q_a_layernorm.weight"] = p["q_norm"][i]
        t[f"{pre}.q_b_proj.weight"] = np.asarray(p["w_uq"][i]).T
    _write_ckpt(str(tmp_path), t, {
        "model_type": "deepseek_v2", "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "q_lora_rank": cfg.q_lora_rank, "kv_lora_rank": cfg.kv_lora_rank,
        "qk_nope_head_dim": cfg.qk_nope_head_dim,
        "qk_rope_head_dim": cfg.qk_rope_head_dim,
        "v_head_dim": cfg.v_head_dim,
        # this synthetic checkpoint stores rope dims in OUR split-half
        # convention; real DeepSeek checkpoints interleave (and default
        # True), which the loader un-permutes — declare it off here
        "rope_interleave": False})
    loaded = load_params(str(tmp_path), dtype=np.float32)
    _assert_tree_close(loaded, p)


def test_loader_mixtral_roundtrip(tmp_path):
    cfg = ModelConfig.tiny(model_type="mixtral", num_experts=2,
                           num_experts_per_tok=1,
                           tie_word_embeddings=False)
    p = llama.init_params(cfg, jax.random.PRNGKey(2), dtype=np.float32)
    t = {}
    _hf_common(p, cfg, t)
    for i in range(cfg.num_layers):
        for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"),
                         ("wv", "v_proj"), ("wo", "o_proj")):
            t[f"model.layers.{i}.self_attn.{hf}.weight"] = \
                np.asarray(p[ours][i]).T
        t[f"model.layers.{i}.block_sparse_moe.gate.weight"] = \
            np.asarray(p["w_router"][i]).T
        for e in range(cfg.num_experts):
            base = f"model.layers.{i}.block_sparse_moe.experts.{e}"
            t[f"{base}.w1.weight"] = np.asarray(p["w_gate"][i, e]).T
            t[f"{base}.w3.weight"] = np.asarray(p["w_up"][i, e]).T
            t[f"{base}.w2.weight"] = np.asarray(p["w_down"][i, e]).T
    _write_ckpt(str(tmp_path), t, {
        "model_type": "mixtral", "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "num_local_experts": 2, "num_experts_per_tok": 1})
    loaded = load_params(str(tmp_path), dtype=np.float32)
    _assert_tree_close(loaded, p)


def test_fetch_model_cli_idempotent(tmp_path, capsys):
    """The DynamoModelRequest seeding Job body: local-dir source copies
    to dest; a complete dest short-circuits (Job retries are free)."""
    import json as _json
    import os

    from dynamo_tpu.models.hub import fetch_model_cli

    src = tmp_path / "src"
    src.mkdir()
    (src / "config.json").write_text(_json.dumps({"model_type": "llama"}))
    (src / "model.safetensors").write_bytes(b"\0" * 16)
    dest = tmp_path / "pvc" / "models" / "m"

    rc = fetch_model_cli(["--model-id", str(src), "--dest", str(dest)])
    assert rc == 0
    assert (dest / "config.json").exists()
    assert (dest / "model.safetensors").exists()
    assert not (dest / ".seeding").exists()

    # second run: must not re-copy (mutate dest, confirm untouched)
    (dest / "model.safetensors").write_bytes(b"\1" * 4)
    rc = fetch_model_cli(["--model-id", str(src), "--dest", str(dest)])
    assert rc == 0
    assert (dest / "model.safetensors").read_bytes() == b"\1" * 4

    # a stale .seeding marker (crashed job) forces a re-copy
    (dest / ".seeding").touch()
    rc = fetch_model_cli(["--model-id", str(src), "--dest", str(dest)])
    assert rc == 0
    assert (dest / "model.safetensors").read_bytes() == b"\0" * 16

    # a CHANGED model id must replace the checkpoint, not short-circuit
    # on the stamp (the recreated seed Job's whole purpose)
    src2 = src.parent / "src2"
    src2.mkdir()
    (src2 / "config.json").write_text(_json.dumps({"model_type": "qwen3"}))
    (src2 / "model.safetensors").write_bytes(b"\2" * 8)
    rc = fetch_model_cli(["--model-id", str(src2), "--dest", str(dest)])
    assert rc == 0
    assert (dest / "model.safetensors").read_bytes() == b"\2" * 8
