"""Shared launcher for forced-device-count subprocess tests.

XLA reads ``--xla_force_host_platform_device_count`` exactly once, at
backend initialization — monkeypatching ``XLA_FLAGS`` inside an already-
running test process is silently ignored. Tests that need a SPECIFIC
device count regardless of the ambient environment (the dynashard
sharded-serving e2e, the multi-host bootstrap smoke) therefore run their
scenario in a subprocess whose environment is assembled here, before any
jax import can happen. One place instead of per-test copy-paste
(ISSUE 12 satellite: test_tp_serving and test_sharded_serving share
this).
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def forced_device_env(devices: int, **extra: object) -> dict:
    """A subprocess environment pinned to ``devices`` virtual CPU
    devices. ``devices <= 1`` strips the forcing flag entirely (one real
    CPU device per process — what the multi-host bootstrap needs)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if devices > 1:
        env["XLA_FLAGS"] = \
            f"--xla_force_host_platform_device_count={devices}"
        env["DYN_FORCE_HOST_DEVICES"] = str(devices)
    else:
        env.pop("DYN_FORCE_HOST_DEVICES", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def run_device_subprocess(script_path, args: Sequence = (), *,
                          devices: int = 8, timeout: float = 600,
                          env_extra: Optional[dict] = None
                          ) -> subprocess.CompletedProcess:
    """Run ``script_path`` under :func:`forced_device_env`. stderr is
    folded into stdout so an assertion message shows the whole story."""
    env = forced_device_env(devices, **(env_extra or {}))
    return subprocess.run(
        [sys.executable, str(script_path), *map(str, args)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout)
