"""Pipeline parallelism (SURVEY §2.4): stage-sharded layers + GPipe
schedule must reproduce the single-device forward exactly, and the
params must genuinely live stage-sharded on the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import init_params, reference_forward
from dynamo_tpu.parallel.mesh import MeshSpec
from dynamo_tpu.parallel.pipeline_parallel import (make_pp_forward,
                                                   shard_params_pp)


def _cfg(layers=8):
    return ModelConfig.tiny(num_layers=layers)


@pytest.mark.parametrize("spec,mb", [
    (MeshSpec(stage=4), 4),     # pure PP
    (MeshSpec(stage=8), 2),     # deep pipeline, short microbatch run
    (MeshSpec(stage=2, data=1), 1),  # single microbatch (max bubble)
])
def test_pp_forward_matches_reference(spec, mb):
    cfg = _cfg(layers=8)
    mesh = spec.build()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    B, T = 4, 12
    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size, (B, T)))

    want = reference_forward(params, cfg, tokens)
    sharded = shard_params_pp(params, mesh)
    got = make_pp_forward(cfg, mesh, num_microbatches=mb)(sharded, tokens)

    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pp_params_actually_sharded():
    cfg = _cfg(layers=8)
    mesh = MeshSpec(stage=4).build()
    params = shard_params_pp(init_params(cfg, jax.random.PRNGKey(1)), mesh)
    # each stage holds 2 of 8 layers of every stacked array
    shard = params["wq"].addressable_shards[0]
    assert shard.data.shape[0] == cfg.num_layers // 4
    # replicated arrays stay whole
    assert (params["embed"].addressable_shards[0].data.shape
            == params["embed"].shape)


def test_pp_rejects_indivisible_layers():
    cfg = _cfg(layers=6)
    mesh = MeshSpec(stage=4).build()
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_forward(cfg, mesh)


def test_pp_forward_qwen3_qk_norm():
    """Regression: per-layer q/k norm weights must stage-shard with the
    rest of the layer stack (a replicated [L, hd] entry desyncs the
    stage body's lax.scan leading axes)."""
    import numpy as np

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshSpec
    from dynamo_tpu.parallel.pipeline_parallel import (make_pp_forward,
                                                       shard_params_pp)

    cfg = ModelConfig.tiny(model_type="qwen3", qk_norm=True, num_layers=4,
                           num_heads=4, num_kv_heads=2, head_dim=16,
                           hidden_size=32, vocab_size=128)
    params_host = llama.init_params(cfg, jax.random.PRNGKey(6))
    # make the norms non-trivial so a dropped/misapplied norm shows up
    params_host["q_norm"] = params_host["q_norm"] * 1.5
    params_host["k_norm"] = params_host["k_norm"] * 0.5
    mesh = MeshSpec(stage=4, data=2).build()
    params = shard_params_pp(params_host, mesh)
    tokens = jnp.asarray(
        np.random.RandomState(6).randint(1, 100, (4, 8)), jnp.int32)
    fn = make_pp_forward(cfg, mesh, num_microbatches=2)
    got = fn(params, tokens)
    ref = llama.reference_forward(params_host, cfg, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
