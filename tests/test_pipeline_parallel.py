"""Pipeline parallelism (SURVEY §2.4): stage-sharded layers + GPipe
schedule must reproduce the single-device forward exactly, and the
params must genuinely live stage-sharded on the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import init_params, reference_forward
from dynamo_tpu.parallel.mesh import MeshSpec
from dynamo_tpu.parallel.pipeline_parallel import (make_pp_forward,
                                                   shard_params_pp)


def _cfg(layers=8):
    return ModelConfig.tiny(num_layers=layers)


@pytest.mark.parametrize("spec,mb", [
    (MeshSpec(stage=4), 4),     # pure PP
    (MeshSpec(stage=8), 2),     # deep pipeline, short microbatch run
    (MeshSpec(stage=2, data=1), 1),  # single microbatch (max bubble)
])
def test_pp_forward_matches_reference(spec, mb):
    cfg = _cfg(layers=8)
    mesh = spec.build()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    B, T = 4, 12
    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size, (B, T)))

    want = reference_forward(params, cfg, tokens)
    sharded = shard_params_pp(params, mesh)
    got = make_pp_forward(cfg, mesh, num_microbatches=mb)(sharded, tokens)

    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pp_params_actually_sharded():
    cfg = _cfg(layers=8)
    mesh = MeshSpec(stage=4).build()
    params = shard_params_pp(init_params(cfg, jax.random.PRNGKey(1)), mesh)
    # each stage holds 2 of 8 layers of every stacked array
    shard = params["wq"].addressable_shards[0]
    assert shard.data.shape[0] == cfg.num_layers // 4
    # replicated arrays stay whole
    assert (params["embed"].addressable_shards[0].data.shape
            == params["embed"].shape)


def test_pp_rejects_indivisible_layers():
    cfg = _cfg(layers=6)
    mesh = MeshSpec(stage=4).build()
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_forward(cfg, mesh)
