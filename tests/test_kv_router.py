"""KV router tests: radix indexer event semantics, cost scheduler behavior,
and the full KV-routed serving graph (2 workers + router + processor +
HTTP frontend) — reference test model: kv_router unit tests +
examples/llm agg_router graph."""

import asyncio

import pytest

from dynamo_tpu.engine.kv_manager import chain_hashes
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.llm.kv_router.protocols import (ForwardPassMetrics,
                                                KvCacheEventWire)
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler

BS = 8  # block size for tests


def ev(worker, kind, hashes, parent=None):
    return KvCacheEventWire(worker_id=worker, kind=kind, block_hashes=hashes,
                            parent_hash=parent)


def test_radix_tree_stored_removed_and_matching():
    idx = KvIndexer(BS)
    tokens = list(range(32))  # 4 blocks
    h = chain_hashes(tokens, BS)

    # worker 1 stores blocks 0..2; worker 2 stores blocks 0..1
    idx.apply_event(ev(1, "stored", h[:3]))
    idx.apply_event(ev(2, "stored", h[:2]))
    scores = idx.find_matches_for_request(tokens).scores
    assert scores == {1: 3, 2: 2}

    # divergent suffix after block 0 only matches its own chain
    other = tokens[:8] + [999] * 24
    oh = chain_hashes(other, BS)
    idx.apply_event(ev(2, "stored", oh[1:3], parent=oh[0]))
    assert idx.find_matches_for_request(other).scores == {1: 1, 2: 3}
    # original chain unchanged
    assert idx.find_matches_for_request(tokens).scores == {1: 3, 2: 2}

    # removal: worker 1 evicts block 2 → overlap shrinks
    idx.apply_event(ev(1, "removed", [h[2]]))
    assert idx.find_matches_for_request(tokens).scores == {1: 2, 2: 2}

    # dead-worker pruning removes all of worker 2's entries
    idx.remove_worker(2)
    assert idx.find_matches_for_request(tokens).scores == {1: 2}
    assert idx.find_matches_for_request(other).scores == {1: 1}


def test_radix_tree_prunes_empty_nodes():
    tree = RadixTree()
    h = chain_hashes(list(range(24)), BS)
    tree.apply_event(ev(7, "stored", h))
    assert tree.block_count() == 3
    tree.apply_event(ev(7, "removed", list(reversed(h))))
    assert tree.block_count() == 0


def metrics(slots=0, total=8, blocks=0, total_blocks=64, waiting=0):
    return ForwardPassMetrics(
        request_active_slots=slots, request_total_slots=total,
        kv_active_blocks=blocks, kv_total_blocks=total_blocks,
        num_requests_waiting=waiting,
        gpu_cache_usage_perc=blocks / max(total_blocks, 1))


def test_scheduler_prefers_cache_overlap():
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    s = KvScheduler(block_size=BS)
    s.update_metrics({1: metrics(), 2: metrics()})
    # worker 2 holds 4 of 4 blocks
    chosen = s.schedule(32, OverlapScores({2: 4}))
    assert chosen == 2


def test_scheduler_balances_load_when_no_overlap():
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    s = KvScheduler(block_size=BS, load_balance_weight=0.7)
    s.update_metrics({1: metrics(slots=7, blocks=60),
                      2: metrics(slots=1, blocks=4)})
    assert s.schedule(32, OverlapScores({})) == 2


def test_scheduler_skips_saturated_and_accounts_optimistically():
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    s = KvScheduler(block_size=BS)
    s.update_metrics({1: metrics(slots=8, total=8),  # slot-saturated
                      2: metrics(total=8)})
    assert s.schedule(16, OverlapScores({1: 2})) == 2
    # keep scheduling onto 2 until its 8 slots fill optimistically
    for _ in range(7):
        assert s.schedule(16, OverlapScores({})) == 2
    with pytest.raises(RuntimeError):
        s.schedule(16, OverlapScores({}))


def test_kv_routed_graph_end_to_end(run_async):
    """Two JAX-engine workers + KvRouter + Processor behind the HTTP
    frontend: identical prompts must route to the same worker (prefix
    affinity) and the index must fill from published events."""

    async def main():
        import aiohttp

        from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.kv_router.router import KvRouter
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.processor import Processor
        from dynamo_tpu.llm.worker import serve_token_model
        from dynamo_tpu.models.config import ModelConfig
        from dynamo_tpu.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        # two workers in one process: use two engines + two DRT attachments
        # so each gets its own lease/instance id
        drt2 = await DistributedRuntime.attach(
            drt.dcp.address.replace("tcp://", ""))

        cfg = ModelConfig.tiny()
        ecfg = EngineConfig(page_size=BS, num_pages=128, max_batch=8,
                            prefill_chunk=64)
        mdc = ModelDeploymentCard(name="routed", tokenizer_kind="byte",
                                  context_length=512,
                                  kv_block_size=BS)
        eng1, eng2 = JaxEngine(cfg, ecfg), JaxEngine(cfg, ecfg, seed=0)
        h1, p1 = await serve_token_model(drt, mdc, eng1, namespace="demo",
                                         component="worker")
        h2, p2 = await serve_token_model(drt2, mdc, eng2, namespace="demo",
                                         component="worker")

        router = KvRouter(drt, "demo", "worker", block_size=BS,
                          scrape_interval=0.2)
        await router.start()
        token_client = await drt.namespace("demo").component("worker") \
            .endpoint("generate_tokens").client()
        await token_client.wait_for_instances()
        processor = Processor(mdc, token_client, router)

        service = HttpService()
        service.manager.add_chat_model("routed", processor.chat)
        service.manager.add_completions_model("routed", processor.completion)
        await service.start(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{service.port}"

        prompt = "shared prefix for cache affinity " * 4
        body = {"model": "routed", "max_tokens": 4,
                "messages": [{"role": "user", "content": prompt}]}
        async with aiohttp.ClientSession() as http:
            async with http.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                first = await r.json()
            # wait for kv events to land in the index
            await asyncio.sleep(0.8)
            assert router.indexer.tree.block_count() > 0

            # the same prompt again must hit the same worker via overlap
            async with http.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200
            stats = router.stats()
            assert stats["decisions"] == 2
            assert stats["avg_hit_rate"] > 0  # second request overlapped

            # completions path through the processor
            async with http.post(f"{base}/v1/completions",
                                 json={"model": "routed", "prompt": "xyz",
                                       "max_tokens": 3}) as r:
                assert r.status == 200
                comp = await r.json()
            assert comp["choices"][0]["finish_reason"] == "length"

        # engines saw disjoint work: exactly one engine served the two
        # routed chat requests (affinity), and hit tokens registered
        served = [(eng1.prompt_tokens_total, eng1.prefix_hit_tokens_total),
                  (eng2.prompt_tokens_total, eng2.prefix_hit_tokens_total)]
        chat_engine = max(served, key=lambda t: t[0])
        assert chat_engine[1] > 0  # prefix cache hit on the repeat

        await router.stop()
        await service.stop()
        for h in (h1, h2):
            await h.stop()
        for p in (p1, p2):
            await p.stop()
        await eng1.stop()
        await eng2.stop()
        await drt2.shutdown()
        await drt.shutdown()

    run_async(main())
