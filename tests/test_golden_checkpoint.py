"""Golden real-checkpoint validation: loader + model + engine vs
`transformers` on an actual HF Llama checkpoint (generated locally with a
fixed seed — fully offline; VERDICT r2 item 6: nothing previously proved
the loader+engine reproduce transformers logits/tokens for a real
checkpoint).

Also covers the hub front door (models/hub.py resolve_model) for the
local-directory case — the path `--model-id` takes on zero-egress hosts.
"""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """A tiny REAL Llama checkpoint written by transformers itself
    (config.json + model.safetensors), plus the live HF model."""
    from transformers import LlamaConfig, LlamaForCausalLM

    tcfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
        attention_bias=False, torch_dtype="float32")
    torch.manual_seed(7)
    model = LlamaForCausalLM(tcfg).eval()
    path = tmp_path_factory.mktemp("golden") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hub_resolves_local_dir(hf_checkpoint):
    from dynamo_tpu.models.hub import resolve_model

    path, _ = hf_checkpoint
    assert resolve_model(path) == path


def test_loader_logits_match_transformers(hf_checkpoint):
    """Full-attention forward on the loaded weights == transformers
    logits (f32, tight tolerance), position by position."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    path, hf = hf_checkpoint
    cfg = ModelConfig.from_local_path(path)
    assert cfg.num_layers == 2 and cfg.num_kv_heads == 2
    params = load_params(path, cfg, dtype=jnp.float32)

    rng = np.random.RandomState(0)
    tokens = rng.randint(1, 128, size=(2, 17)).astype(np.int32)
    ours = np.asarray(llama.reference_forward(params, cfg,
                                              jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_engine_generation_matches_transformers_generate(hf_checkpoint,
                                                         run_async):
    """The SERVING path (paged prefill + pipelined fused-window decode)
    greedy-generates exactly what transformers.generate does on the same
    checkpoint — loader, paging, windowing, sampling all on the line."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.runtime.engine import Context

    path, hf = hf_checkpoint
    cfg = ModelConfig.from_local_path(path)
    params = load_params(path, cfg, dtype=jnp.float32)
    N = 12
    prompt = [(i * 11) % 120 + 1 for i in range(21)]
    with torch.no_grad():
        want = hf.generate(torch.tensor([prompt], dtype=torch.long),
                           max_new_tokens=N, do_sample=False,
                           pad_token_id=0)[0, len(prompt):].tolist()

    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=16, prefill_buckets=(16,),
                        batch_buckets=(4,), page_buckets=(16,),
                        decode_steps=4)
    engine = JaxEngine(cfg, ecfg, params=params)

    async def gen():
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=N, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    got = run_async(gen())
    assert got == want, f"engine {got} vs transformers {want}"


@pytest.fixture(scope="module")
def gemma_checkpoint(tmp_path_factory):
    """A tiny REAL Gemma checkpoint (scaled embeddings, (1+w) norm,
    GeGLU, tied head) written by transformers itself."""
    from transformers import GemmaConfig, GemmaForCausalLM

    tcfg = GemmaConfig(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=10000.0, tie_word_embeddings=True,
        hidden_activation="gelu_pytorch_tanh", torch_dtype="float32")
    torch.manual_seed(11)
    model = GemmaForCausalLM(tcfg).eval()
    path = tmp_path_factory.mktemp("golden_gemma") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_gemma_logits_match_transformers(gemma_checkpoint):
    """Gemma family: all four semantic switches (embed scale, unit-offset
    norm, GeGLU, tied head) against the HF oracle."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    path, hf = gemma_checkpoint
    cfg = ModelConfig.from_local_path(path)
    assert cfg.model_type == "gemma"
    assert cfg.embed_scale and cfg.norm_unit_offset
    assert cfg.hidden_act == "gelu_tanh" and cfg.tie_word_embeddings
    params = load_params(path, cfg, dtype=jnp.float32)
    assert "lm_head" not in params

    rng = np.random.RandomState(1)
    tokens = rng.randint(1, 160, size=(2, 15)).astype(np.int32)
    ours = np.asarray(llama.reference_forward(params, cfg,
                                              jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)


def test_gemma_engine_generation_matches_transformers(gemma_checkpoint,
                                                      run_async):
    """The full serving path (paged prefill + fused-window decode) on a
    Gemma checkpoint greedy-matches transformers.generate."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.runtime.engine import Context

    path, hf = gemma_checkpoint
    cfg = ModelConfig.from_local_path(path)
    params = load_params(path, cfg, dtype=jnp.float32)
    N = 10
    prompt = [(i * 13) % 150 + 1 for i in range(18)]
    with torch.no_grad():
        want = hf.generate(torch.tensor([prompt], dtype=torch.long),
                           max_new_tokens=N, do_sample=False,
                           pad_token_id=0)[0, len(prompt):].tolist()

    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=16, prefill_buckets=(16,),
                        batch_buckets=(4,), page_buckets=(16,),
                        decode_steps=4)
    engine = JaxEngine(cfg, ecfg, params=params)

    async def gen():
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=N, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    got = run_async(gen())
    assert got == want, f"engine {got} vs transformers {want}"


@pytest.fixture(scope="module")
def gemma2_checkpoint(tmp_path_factory):
    """A tiny REAL Gemma-2 checkpoint: everything Gemma-1 has PLUS
    sandwich norms, attention/final logit softcaps, an explicit
    query_pre_attn_scalar, and a sliding window (set to 8 — well under
    the test sequence lengths, so the window actually masks)."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    tcfg = Gemma2Config(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=10000.0, tie_word_embeddings=True,
        hidden_activation="gelu_pytorch_tanh", query_pre_attn_scalar=16,
        sliding_window=8, attn_logit_softcapping=30.0,
        final_logit_softcapping=20.0, torch_dtype="float32",
        attn_implementation="eager")
    torch.manual_seed(13)
    model = Gemma2ForCausalLM(tcfg).eval()
    path = tmp_path_factory.mktemp("golden_gemma2") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_gemma2_logits_match_transformers(gemma2_checkpoint):
    """Gemma-2 semantics against the HF oracle: sandwich norms, attention
    softcap, sliding window on layer 0 (global on layer 1), final softcap.
    Sequence length 24 > window 8 so sliding masking is load-bearing."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    path, hf = gemma2_checkpoint
    cfg = ModelConfig.from_local_path(path)
    assert cfg.model_type == "gemma2"
    assert cfg.sandwich_norms and cfg.sliding_window == 8
    assert cfg.attn_logit_softcap == 30.0
    assert cfg.final_logit_softcap == 20.0
    assert cfg.query_pre_attn_scalar == 16
    params = load_params(path, cfg, dtype=jnp.float32)
    assert "ln_attn_post" in params and "ln_mlp_post" in params

    rng = np.random.RandomState(2)
    tokens = rng.randint(1, 160, size=(2, 24)).astype(np.int32)
    ours = np.asarray(llama.reference_forward(params, cfg,
                                              jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("kernels", [False, True])
def test_gemma2_engine_generation_matches_transformers(gemma2_checkpoint,
                                                       run_async,
                                                       monkeypatch,
                                                       kernels):
    """Full serving path on a Gemma-2 checkpoint greedy-matches
    transformers.generate across the sliding-window boundary — on the
    XLA attention paths AND on the Pallas kernel paths (flash prefill +
    fused-window decode in interpret mode), which implement the score
    softcap and per-layer sliding window natively."""
    if kernels:
        monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("DYN_PREFILL_PALLAS", "1")
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.runtime.engine import Context

    path, hf = gemma2_checkpoint
    cfg = ModelConfig.from_local_path(path)
    params = load_params(path, cfg, dtype=jnp.float32)
    N = 10
    prompt = [(i * 17) % 150 + 1 for i in range(18)]  # 18 > window 8
    with torch.no_grad():
        want = hf.generate(torch.tensor([prompt], dtype=torch.long),
                           max_new_tokens=N, do_sample=False,
                           pad_token_id=0)[0, len(prompt):].tolist()

    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=16, prefill_buckets=(16,),
                        batch_buckets=(4,), page_buckets=(16,),
                        decode_steps=4)
    engine = JaxEngine(cfg, ecfg, params=params)

    async def gen():
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=N, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    got = run_async(gen())
    assert got == want, f"engine {got} vs transformers {want}"


@pytest.fixture(scope="module")
def qwen3_checkpoint(tmp_path_factory):
    """A tiny REAL Qwen3 checkpoint: Llama GQA shape + per-head q/k
    RMSNorm before RoPE, no qkv bias."""
    from transformers import Qwen3Config, Qwen3ForCausalLM

    tcfg = Qwen3Config(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=10000.0, tie_word_embeddings=False,
        torch_dtype="float32", attn_implementation="eager")
    torch.manual_seed(17)
    model = Qwen3ForCausalLM(tcfg).eval()
    path = tmp_path_factory.mktemp("golden_qwen3") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_qwen3_logits_match_transformers(qwen3_checkpoint):
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    path, hf = qwen3_checkpoint
    cfg = ModelConfig.from_local_path(path)
    assert cfg.model_type == "qwen3" and cfg.qk_norm
    assert not cfg.attn_bias
    params = load_params(path, cfg, dtype=jnp.float32)
    assert "q_norm" in params and "k_norm" in params

    rng = np.random.RandomState(5)
    tokens = rng.randint(1, 160, size=(2, 17)).astype(np.int32)
    ours = np.asarray(llama.reference_forward(params, cfg,
                                              jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)


def test_qwen3_engine_generation_matches_transformers(qwen3_checkpoint,
                                                      run_async):
    """Serving path (paged prefill + fused-window decode) on Qwen3
    greedy-matches transformers.generate."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.runtime.engine import Context

    path, hf = qwen3_checkpoint
    cfg = ModelConfig.from_local_path(path)
    params = load_params(path, cfg, dtype=jnp.float32)
    N = 8
    prompt = [(i * 11) % 150 + 1 for i in range(14)]
    with torch.no_grad():
        want = hf.generate(torch.tensor([prompt], dtype=torch.long),
                           max_new_tokens=N, do_sample=False,
                           pad_token_id=0)[0, len(prompt):].tolist()

    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=16, prefill_buckets=(16,),
                        batch_buckets=(4,), page_buckets=(16,),
                        decode_steps=4)
    engine = JaxEngine(cfg, ecfg, params=params)

    async def gen():
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=N, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    got = run_async(gen())
    assert got == want, f"engine {got} vs transformers {want}"


def test_qwen3_moe_logits_match_transformers(tmp_path_factory):
    """Qwen3-MoE: per-head q/k norms + Qwen-named experts (mlp.experts.N
    gate/up/down_proj, router mlp.gate) through the dense-over-experts
    MoE path; logits vs the HF oracle."""
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    tcfg = Qwen3MoeConfig(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=10000.0, tie_word_embeddings=False,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        torch_dtype="float32", attn_implementation="eager")
    torch.manual_seed(19)
    model = Qwen3MoeForCausalLM(tcfg).eval()
    path = tmp_path_factory.mktemp("golden_qwen3moe") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    cfg = ModelConfig.from_local_path(str(path))
    assert cfg.model_type == "qwen3" and cfg.qk_norm
    assert cfg.num_experts == 4 and cfg.intermediate_size == 48
    params = load_params(str(path), cfg, dtype=jnp.float32)

    rng = np.random.RandomState(6)
    tokens = rng.randint(1, 160, size=(2, 13)).astype(np.int32)
    ours = np.asarray(llama.reference_forward(params, cfg,
                                              jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)


def _deepseek_v2_cfg(**over):
    from transformers import DeepseekV2Config

    base = dict(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        kv_lora_rank=16, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, head_dim=8,
        max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
        tie_word_embeddings=False, n_routed_experts=None,
        # HF builds a MoE block for every layer_idx >= first_k_dense_replace
        # even when n_routed_experts is None — an all-dense model needs the
        # threshold past the last layer
        first_k_dense_replace=99,
        torch_dtype="float32", attn_implementation="eager")
    base.update(over)
    return DeepseekV2Config(**base)


def test_deepseek_v2_dense_logits_match_transformers(tmp_path_factory):
    """Dense MLA against the HF oracle — the first direct transformers
    cross-check of the MLA stack, which also validates the interleaved→
    split-half rope weight permutation real DeepSeek checkpoints need."""
    from transformers import DeepseekV2ForCausalLM

    from dynamo_tpu.models import mla
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    torch.manual_seed(23)
    model = DeepseekV2ForCausalLM(_deepseek_v2_cfg()).eval()
    path = tmp_path_factory.mktemp("golden_dsv2") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    cfg = ModelConfig.from_local_path(str(path))
    assert cfg.is_mla and cfg.rope_interleave and cfg.num_experts == 0
    params = load_params(str(path), cfg, dtype=jnp.float32)
    rng = np.random.RandomState(9)
    tokens = rng.randint(1, 160, size=(2, 12)).astype(np.int32)
    ours = np.asarray(mla.reference_forward(params, cfg,
                                            jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)


def test_deepseek_v2_norm_topk_prob_rejected():
    """transformers' DeepseekV2MoEGate ignores norm_topk_prob while
    DeepSeek's remote-code gate renormalizes-instead-of-scales — with
    conflicting oracles (and no published V2 checkpoint setting it) the
    config must be rejected loudly, not silently served either way."""
    from dynamo_tpu.models.config import ModelConfig

    hf = dict(model_type="deepseek_v2", vocab_size=160, hidden_size=64,
              intermediate_size=128, num_hidden_layers=2,
              num_attention_heads=4, num_key_value_heads=4,
              kv_lora_rank=16, n_routed_experts=8, num_experts_per_tok=2,
              moe_intermediate_size=32, norm_topk_prob=True)
    with pytest.raises(NotImplementedError, match="norm_topk_prob"):
        ModelConfig.from_hf_config(hf)
    hf["norm_topk_prob"] = False
    assert ModelConfig.from_hf_config(hf).moe_router == "deepseek_v2"


def test_deepseek_v2_moe_serving_matches_transformers(tmp_path_factory,
                                                      run_async):
    """DeepSeek-V2 MoE (dense first-k layers, shared experts, group-
    limited softmax routing with scaling): oracle logits AND the full
    serving path (paged prefill + fused-window decode through the
    segmented stack) greedy-match transformers."""
    from transformers import DeepseekV2ForCausalLM

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models import mla
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.runtime.engine import Context

    torch.manual_seed(29)
    model = DeepseekV2ForCausalLM(_deepseek_v2_cfg(
        q_lora_rank=24, n_routed_experts=8, num_experts_per_tok=2,
        moe_intermediate_size=32, n_shared_experts=2,
        first_k_dense_replace=1, moe_layer_freq=1,
        topk_method="group_limited_greedy", n_group=4, topk_group=2,
        routed_scaling_factor=1.5, norm_topk_prob=False,
        aux_loss_alpha=0.0, seq_aux=False)).eval()
    path = tmp_path_factory.mktemp("golden_dsv2moe") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    cfg = ModelConfig.from_local_path(str(path))
    assert cfg.num_experts == 8 and cfg.n_shared_experts == 2
    assert cfg.first_k_dense_replace == 1 and cfg.n_group == 4
    assert cfg.moe_router == "deepseek_v2"
    params = load_params(str(path), cfg, dtype=jnp.float32)

    rng = np.random.RandomState(10)
    tokens = rng.randint(1, 160, size=(2, 12)).astype(np.int32)
    ours = np.asarray(mla.reference_forward(params, cfg,
                                            jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)

    N = 8
    prompt = [(i * 7) % 150 + 1 for i in range(11)]
    with torch.no_grad():
        want = model.generate(torch.tensor([prompt], dtype=torch.long),
                              max_new_tokens=N, do_sample=False,
                              pad_token_id=0)[0, len(prompt):].tolist()
    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=16, prefill_buckets=(16,),
                        batch_buckets=(4,), page_buckets=(16,),
                        decode_steps=4)
    engine = JaxEngine(cfg, ecfg, params=params)

    async def gen():
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=N, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    got = run_async(gen())
    assert got == want, f"engine {got} vs transformers {want}"


def test_deepseek_v3_moe_logits_match_transformers(tmp_path_factory):
    """DeepSeek-V3 routing (sigmoid scores + e_score_correction_bias
    selection, top-2-sum group limiting, renormalized weights, scaling)
    against the HF oracle."""
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM

    from dynamo_tpu.models import mla
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params

    tcfg = DeepseekV3Config(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        kv_lora_rank=16, q_lora_rank=24, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, head_dim=8,
        max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
        tie_word_embeddings=False, n_routed_experts=8,
        num_experts_per_tok=2, moe_intermediate_size=32,
        n_shared_experts=1, first_k_dense_replace=1, n_group=4,
        topk_group=2, routed_scaling_factor=2.0, norm_topk_prob=True,
        rope_interleave=True, torch_dtype="float32",
        attn_implementation="eager")
    torch.manual_seed(31)
    model = DeepseekV3ForCausalLM(tcfg).eval()
    # give the selection bias real (nonzero) values so the bias-vs-weight
    # distinction is load-bearing
    with torch.no_grad():
        for layer in model.model.layers[1:]:
            layer.mlp.gate.e_score_correction_bias.uniform_(-0.5, 0.5)
    path = tmp_path_factory.mktemp("golden_dsv3") / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    cfg = ModelConfig.from_local_path(str(path))
    assert cfg.moe_router == "deepseek_v3" and cfg.norm_topk_prob
    params = load_params(str(path), cfg, dtype=jnp.float32)

    rng = np.random.RandomState(11)
    tokens = rng.randint(1, 160, size=(2, 12)).astype(np.int32)
    ours = np.asarray(mla.reference_forward(params, cfg,
                                            jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)
