"""Host-DRAM KV offload tier (reference lib/llm/src/kv V2 multi-tier
storage + docs/kv_cache_manager.md: evicted blocks spill to host memory and
restore on prefix hits)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.kv_manager import PageManager, chain_hashes


def _commit_all(pm, pages, prompt):
    hashes = chain_hashes(prompt, pm.page_size)
    for i, h in enumerate(hashes):
        pm.commit(pages[i], h, parent_hash=hashes[i - 1] if i else None)


def test_offload_on_eviction_and_restore():
    pm = PageManager(num_pages=4, page_size=4, host_pages=8)  # 3 usable
    prompt = list(range(12))  # 3 blocks
    alloc = pm.allocate_sequence(prompt)
    pages, cached = alloc
    assert cached == 0
    _commit_all(pm, pages, prompt)
    pm.drain_events()
    pm.release_sequence(pages)

    # a different prompt evicts all three pages → offload copies queued,
    # NO removed events (blocks stay matchable via the host tier)
    other = list(range(100, 112))
    alloc2 = pm.allocate_sequence(other)
    assert alloc2 is not None
    off, res = pm.drain_tier_ops()
    assert len(off) == 3 and not res
    assert not [e for e in pm.drain_events() if e.kind == "removed"]
    _commit_all(pm, alloc2.pages, other)
    pm.release_sequence(alloc2.pages)

    # original prompt again: blocks hit in the HOST tier → fresh pages with
    # queued restores, counted as cached tokens (2 full blocks, tail capped)
    alloc3 = pm.allocate_sequence(prompt)
    assert alloc3.cached_tokens == 8
    assert len(alloc3.restores) == 2
    off, res = pm.drain_tier_ops()
    assert len(res) == 2
    # restored blocks are matchable on-device again
    h = chain_hashes(prompt, 4)
    assert pm.by_hash[h[0]] == alloc3.pages[0]


def test_restore_then_evict_skips_recopy():
    pm = PageManager(num_pages=3, page_size=2, host_pages=4)  # 2 usable
    p1 = list(range(4))
    a = pm.allocate_sequence(p1)
    _commit_all(pm, a.pages, p1)
    pm.release_sequence(a.pages)
    b = pm.allocate_sequence(list(range(10, 14)))  # evict both
    pm.drain_tier_ops()
    pm.release_sequence(b.pages)
    c = pm.allocate_sequence(p1)  # restore block 0 from host
    assert len(c.restores) == 1
    pm.drain_tier_ops()
    pm.release_sequence(c.pages)
    # evict the restored page again: content still on host → no new offload
    d = pm.allocate_sequence(list(range(20, 24)))
    off, _ = pm.drain_tier_ops()
    restored_page = c.restores[0][0]
    assert restored_page not in [p for p, _ in off]
    assert d is not None


def test_host_lru_eviction_emits_removed():
    pm = PageManager(num_pages=3, page_size=2, host_pages=1)  # 2 usable
    p1 = list(range(4))
    a = pm.allocate_sequence(p1)
    _commit_all(pm, a.pages, p1)
    pm.release_sequence(a.pages)
    pm.drain_events()
    # evicting 2 committed pages into a 1-slot host tier: the second
    # offload must LRU-evict the first block → removed event for it
    b = pm.allocate_sequence(list(range(10, 14)))
    assert b is not None
    off, _ = pm.drain_tier_ops()
    removed = [e for e in pm.drain_events() if e.kind == "removed"]
    assert len(off) >= 1
    assert len(removed) >= 1


def test_stale_restore_dropped_on_page_recycle():
    """A queued restore whose target page is released and recycled before
    any drain must NOT fire (it would clobber the new owner)."""
    pm = PageManager(num_pages=3, page_size=2, host_pages=4)
    p1 = list(range(4))
    a = pm.allocate_sequence(p1)
    _commit_all(pm, a.pages, p1)
    pm.release_sequence(a.pages)
    b = pm.allocate_sequence(list(range(10, 14)))  # spill to host
    pm.drain_tier_ops()
    pm.release_sequence(b.pages)
    c = pm.allocate_sequence(p1)  # queues a restore
    assert len(c.restores) == 1
    pm.release_sequence(c.pages)  # cancelled before any step
    d = pm.allocate_sequence(list(range(20, 24)))  # recycles the page
    _, res = pm.drain_tier_ops()
    assert res == []  # stale restore dropped
    assert d is not None


@pytest.mark.parametrize("host_pages", [0, 64])
def test_engine_offload_end_to_end(host_pages, run_async):
    """Evict a prompt's KV out of a tiny HBM pool, then re-issue the
    prompt: with a host tier the continuation must be identical (restored
    content, not recomputed garbage) and count as a prefix hit."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=24, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,),
                        host_pages=host_pages, watermark_pages=2,
                        host_tier_int8=False)  # identity asserts lossless
    engine = JaxEngine(cfg, ecfg, seed=0)

    async def gen(prompt, n=8):
        req = PreprocessedRequest(
            token_ids=prompt, sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        return toks

    async def scenario():
        rng = np.random.RandomState(0)
        prompt_a = rng.randint(1, 500, 24).tolist()
        first = await gen(prompt_a)
        # churn through enough other prompts to evict A's pages
        for i in range(4):
            await gen(rng.randint(1, 500, 24).tolist())
        hits_before = engine.prefix_hit_tokens_total
        again = await gen(prompt_a)
        await engine.stop()
        return first, again, engine.prefix_hit_tokens_total - hits_before

    first, again, hits = run_async(scenario())
    assert len(first) == 8
    assert first == again  # greedy: identical continuation either way
    if host_pages:
        assert hits > 0, "host tier should have produced prefix hits"
        assert engine.restore_pages_total > 0
        assert engine.offload_pages_total > 0
    else:
        assert engine.restore_pages_total == 0


def test_engine_chunked_restore_token_identity(run_async):
    """tier_restore_chunk=1: a multi-page host hit must drain its
    restores over SEVERAL iterations (sequence gated meanwhile) and still
    reproduce the unchunked continuation exactly."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()

    def run(chunk):
        ecfg = EngineConfig(page_size=4, num_pages=24, max_batch=4,
                            prefill_chunk=32, prefill_buckets=(32,),
                            batch_buckets=(4,), page_buckets=(16,),
                            host_pages=64, watermark_pages=2,
                            tier_restore_chunk=chunk,
                            host_tier_int8=False)  # identity: lossless
        engine = JaxEngine(cfg, ecfg, seed=0)

        async def gen(prompt, n=8):
            req = PreprocessedRequest(
                token_ids=prompt, sampling=SamplingOptions(),
                stop=StopConditions(max_tokens=n, ignore_eos=True),
                eos_token_ids=[])
            toks = []
            async for out in engine.generate(req, Context()):
                toks.extend(out.token_ids)
                if out.finish_reason:
                    break
            return toks

        async def scenario():
            rng = np.random.RandomState(1)
            prompt_a = rng.randint(1, 500, 24).tolist()  # 6 pages
            first = await gen(prompt_a)
            for _ in range(4):  # churn A out of the 23-page HBM pool
                await gen(rng.randint(1, 500, 24).tolist())
            again = await gen(prompt_a)
            await engine.stop()
            return first, again, engine.restore_pages_total

        return run_async(scenario())

    first_c, again_c, restored_c = run(1)     # one page per iteration
    first_u, again_u, restored_u = run(0)     # unchunked baseline
    assert first_c == again_c == first_u == again_u
    assert restored_c > 1 and restored_c == restored_u


def test_restore_slots_pinned_against_midalloc_eviction():
    """Regression (ADVICE r1 high): slots planned for restore must be
    pinned for the whole allocate_sequence call. Previously they reached
    pending_restore only at the end, so _pop_fresh→_host_slot evictions
    fired by the same call's fresh-page pops could reassign them to new
    offloads — the engine drains offloads before restores, so the restore
    then copied the WRONG block into a page registered under the original
    hash (silent KV corruption), or raised KeyError at host_by_hash[h]."""
    pm = PageManager(num_pages=6, page_size=2, host_pages=2)  # 5 usable
    a_prompt = list(range(4))           # blocks A0, A1
    a = pm.allocate_sequence(a_prompt)
    _commit_all(pm, a.pages, a_prompt)
    pm.release_sequence(a.pages)
    hold = pm.allocate_sequence([50, 51])          # keeps one page active
    b_prompt = list(range(10, 14))
    b = pm.allocate_sequence(b_prompt)             # pops remaining free
    _commit_all(pm, b.pages, b_prompt)
    pm.release_sequence(b.pages)
    c_prompt = list(range(20, 24))
    c = pm.allocate_sequence(c_prompt)   # free empty → evicts A's pages
    off, _ = pm.drain_tier_ops()
    assert len(off) == 2                 # A0, A1 offloaded; host tier FULL
    _commit_all(pm, c.pages, c_prompt)
    pm.release_sequence(c.pages)
    pm.drain_events()

    # A's prefix again (+2 tokens): both host slots are restore-planned;
    # the 3 fresh-page pops evict committed pages (B, C) into the full
    # host tier mid-call. Pinning must refuse them slots 0/1.
    d = pm.allocate_sequence(a_prompt + [98, 99])
    assert d is not None
    assert len(d.restores) == 2
    assert d.cached_tokens == 4
    off, res = pm.drain_tier_ops()
    assert sorted(s for _, s in res) == [0, 1]
    # no slot may be both an offload target and a restore source
    assert not ({s for _, s in off} & {s for _, s in res})
    # the restored blocks still live in the host tier under their hashes
    ha = chain_hashes(a_prompt, 2)
    assert pm.host_by_hash[ha[0]] == d.restores[0][1]
    assert pm.host_by_hash[ha[1]] == d.restores[1][1]
    # evicted-without-a-slot blocks left the worker entirely → removed
    removed = [e for e in pm.drain_events() if e.kind == "removed"]
    assert removed, "pinned-out evictions must emit removed events"
    assert pm._pinned_slots == set()     # pins released after the call
    pm.release_sequence(hold.pages)


def test_alloc_accounting_with_reusable_prefix_hits():
    """Regression: device prefix hits that are refcount-0 (reusable) must
    not count as poppable capacity — previously the OOM check passed and
    _pop_fresh raised on an empty pool mid-allocation."""
    pm = PageManager(num_pages=5, page_size=2)  # 4 usable
    prompt = list(range(8))  # 4 blocks
    a = pm.allocate_sequence(prompt)
    assert a is not None
    _commit_all(pm, a.pages, prompt)
    pm.release_sequence(a.pages)  # all 4 committed + reusable
    # same prompt: 3 blocks reusable-hit (tail capped), needs 1 fresh;
    # only the hit pages themselves are "available" → must refuse, not
    # crash
    b = pm.allocate_sequence(prompt + [99, 100])  # 5 blocks total
    assert b is None or len(b.pages) == 5  # no KeyError either way
    # and a plain repeat allocation still works
    c = pm.allocate_sequence(prompt)
    assert c is not None
    assert c.cached_tokens == 6


def test_mla_engine_host_tier_end_to_end(run_async):
    """MLA (latent+rope pools with DIFFERENT last dims) through the host
    tier: host pool shapes must derive from the device pools — deriving
    them from GQA config fields allocated wrong shapes and crashed the
    first offload landing (round-5 latent bug). Restore must be
    token-identical (tier is lossless here)."""
    import jax

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny(model_type="deepseek_v2", kv_lora_rank=16,
                           qk_nope_head_dim=16, qk_rope_head_dim=8,
                           v_head_dim=16, q_lora_rank=24)
    ecfg = EngineConfig(page_size=4, num_pages=24, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,),
                        host_pages=64, watermark_pages=2,
                        host_tier_int8=False)  # identity asserts lossless
    engine = JaxEngine(cfg, ecfg, seed=0)
    assert engine.host_k.shape[2:] == engine.kv_k.shape[2:]
    assert engine.host_v.shape[2:] == engine.kv_v.shape[2:]
    assert engine.host_k.shape[-1] != engine.host_v.shape[-1]  # MLA!

    async def gen(prompt, n=6):
        req = PreprocessedRequest(
            token_ids=prompt, sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        return toks

    async def scenario():
        rng = np.random.RandomState(3)
        prompt_a = rng.randint(1, 500, 24).tolist()
        first = await gen(prompt_a)
        for i in range(4):
            await gen(rng.randint(1, 500, 24).tolist())
        again = await gen(prompt_a)
        await engine.stop()
        return first, again

    first, again = run_async(scenario())
    assert len(first) == 6
    assert first == again
    assert engine.offload_pages_total > 0
    assert engine.restore_pages_total > 0
