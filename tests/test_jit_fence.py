"""Runtime compile fence (engine/jit_fence.py) + bucket-grid coverage.

The engine's zero-compile serving invariant has two enforcement layers:
dynajit (static, tests/test_lint.py) and the runtime fence tested here —
armed by ``warmup()``, it counts every post-warmup XLA compile via JAX's
monitoring hook. The e2e test drives a mixed prefill/decode/spec
workload through a warmed CPU engine and pins the counter at ZERO: this
is the regression gate for the ROADMAP item-3 hot-path refactor (any
change that lets an unbucketed shape or a mismatched call form reach a
jitted entry fails here, not on a chip). It guards, among others, the
two warmup bugs the fence found when first armed: explicit-vs-defaulted
``penalties=None`` / ``logprobs_topn=0`` kwargs keying different jit
cache entries than the warmed forms.
"""

import asyncio
import logging

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.engine.jit_fence import (COMPILE_EVENT, CompileFence,
                                         PostWarmupCompileError)
from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                             SamplingOptions,
                                             StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime import Context

# ------------------------------------------------------------- fence unit


def _fresh_jit_compile(salt: int):
    """Force a real backend compile (a never-seen-before jaxpr/shape)."""
    f = jax.jit(lambda x: x * 2 + salt)
    f(jnp.zeros((salt % 7 + 1,)))


def test_fence_counts_only_when_armed():
    fence = CompileFence("t1", mode="")
    _fresh_jit_compile(101)          # before arm: not counted
    assert fence.post_warmup_compiles == 0
    fence.arm()
    _fresh_jit_compile(102)
    assert fence.post_warmup_compiles >= 1
    n = fence.post_warmup_compiles
    fence.disarm()
    _fresh_jit_compile(103)
    assert fence.post_warmup_compiles == n


def test_fence_warn_mode_logs(caplog):
    fence = CompileFence("t2", mode="warn")
    fence.arm()
    with caplog.at_level(logging.WARNING, "dynamo_tpu.engine.fence"):
        _fresh_jit_compile(104)
    fence.disarm()
    assert any("XLA compile after warmup" in r.message
               for r in caplog.records)


def test_fence_messages_name_last_dispatched_form(caplog):
    """A tripped fence names the offending call form — jit name plus
    per-operand dtype[shape] and static kwarg values — from the note the
    engine's dispatch wrapper stamps via note_dispatch (raw refs on the
    hot path, rendered only here on the trip path)."""
    fence = CompileFence("t2b", mode="warn")
    assert fence.last_dispatch_form() == "<no dispatch recorded>"
    fence.note_dispatch("decode_multi_fn",
                        (jnp.zeros((2, 8), jnp.bfloat16), 3),
                        {"k_steps": 2, "logprobs_topn": 20})
    form = fence.last_dispatch_form()
    assert form.startswith("decode_multi_fn(")
    assert "bfloat16[2,8]" in form
    assert "logprobs_topn=20" in form
    fence.arm()
    with caplog.at_level(logging.WARNING, "dynamo_tpu.engine.fence"):
        _fresh_jit_compile(107)
    fence.disarm()
    assert any("last dispatched form" in r.getMessage()
               and "decode_multi_fn(" in r.getMessage()
               for r in caplog.records)


def test_fence_raise_mode_names_form():
    fence = CompileFence("t3b", mode="raise")
    fence.note_dispatch("prefill_fn",
                        (jnp.zeros((4,), jnp.int32),), None)
    fence.arm()
    try:
        with pytest.raises(PostWarmupCompileError,
                           match=r"prefill_fn\(int32\[4\]\)"):
            _fresh_jit_compile(108)
    finally:
        fence.disarm()


def test_fence_raise_mode():
    fence = CompileFence("t3", mode="raise")
    fence.arm()
    try:
        with pytest.raises(PostWarmupCompileError):
            _fresh_jit_compile(105)
    finally:
        fence.disarm()


def test_fence_mode_reads_env(monkeypatch):
    fence = CompileFence("t4")
    assert fence.mode == ""
    monkeypatch.setenv("DYN_JIT_FENCE", "warn")
    assert fence.mode == "warn"


def test_fence_records_timeline_event():
    from dynamo_tpu.runtime.tracing import StepTimeline

    tl = StepTimeline(16)
    fence = CompileFence("t5", timeline=tl, mode="")
    fence.arm()
    _fresh_jit_compile(106)
    fence.disarm()
    kinds = [e["kind"] for e in tl.snapshot()]
    assert "compile" in kinds


# --------------------------------------------------- bucket-grid coverage


@pytest.mark.parametrize("ecfg", [
    EngineConfig(),                                        # the default
    EngineConfig(page_size=8, num_pages=64, max_batch=8,   # max_batch not
                 prefill_chunk=32, batch_buckets=(1, 2, 4),  # in buckets
                 prefill_buckets=(16,), page_buckets=(8,)),
    EngineConfig(page_size=8, num_pages=128, max_batch=6,  # chunk beyond
                 prefill_chunk=64, batch_buckets=(1, 2),   # last bucket,
                 prefill_buckets=(8,), page_buckets=(4, 16)),  # via 2x
])
def test_bucket_grid_covers_every_reachable_shape(ecfg):
    """Every shape the bucket helpers can produce for an admissible
    request must be in warmed_grid() — _pick doubles past its last
    bucket, so the declared tuples alone under-cover exotic configs
    (serving would compile mid-flight; the old warmup did exactly
    that for these configs)."""
    grid = ecfg.warmed_grid()
    cap_pages = min(ecfg.page_buckets[-1], max(ecfg.num_pages - 1, 1))
    for n in range(1, ecfg.prefill_chunk + 1):
        assert ecfg.bucket_len(n) in grid["prefill_lens"]
    for n in range(1, ecfg.max_batch + 1):
        assert ecfg.bucket_batch(n) in grid["decode_batches"]
        assert ecfg.prefill_bucket_batch(n) in grid["prefill_batches"]
    for n in range(1, cap_pages + 1):
        assert ecfg.bucket_pages(n) in grid["page_buckets"]


def test_default_grid_matches_declared_buckets():
    """On the DEFAULT config the exact image equals the declared tuples,
    so the warmed-grid rework changed no default warmup program set."""
    ecfg = EngineConfig()
    grid = ecfg.warmed_grid()
    assert grid["prefill_lens"] == sorted(ecfg.prefill_buckets)
    assert grid["decode_batches"] == sorted(ecfg.batch_buckets)
    assert grid["page_buckets"] == sorted(ecfg.page_buckets)


# ------------------------------------------------------------- fence e2e


def _req(tokens, mt=6, **sampling):
    return PreprocessedRequest(
        token_ids=list(tokens), sampling=SamplingOptions(**sampling),
        stop=StopConditions(max_tokens=mt, ignore_eos=True),
        eos_token_ids=[])


def test_fence_zero_compiles_mixed_workload(caplog):
    """The tier-1 zero-compile gate: warm a tiny CPU engine (spec decode
    on, fused pipelined windows), then drive a mixed prefill/decode/spec
    workload — spec-friendly greedy prompts, a sampled row (window
    fallback arm), prompt lengths crossing both prefill buckets,
    concurrent admission — and assert NOT ONE XLA compile happened
    after warmup. Then an intentionally unbucketed jit call trips the
    fence in warn mode."""
    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_batch=4,
                        prefill_chunk=32, batch_buckets=(1, 2, 4),
                        prefill_buckets=(16, 32), page_buckets=(8,),
                        max_prefill_batch=2, decode_steps=2,
                        spec_decode=True, spec_tokens=2)
    eng = JaxEngine(cfg, ecfg, seed=0)
    eng.warmup()
    assert eng.fence.armed

    async def one(r):
        toks = []
        async for out in eng.generate(r, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason is not None:
                assert out.finish_reason != "error"
        return toks

    async def main():
        reqs = [_req([5, 6, 7, 5, 6, 7, 5, 6] * 2),     # spec-friendly
                _req(list(range(1, 20))),               # 19 tok prompt
                _req([9, 9, 9, 9, 9, 9, 9, 9] * 3),     # spec-friendly
                _req(list(range(30, 41)),
                     temperature=0.9, seed=7),          # sampled fallback
                _req(list(range(50, 55)), mt=4)]        # short row
        out = await asyncio.gather(*(one(r) for r in reqs))
        await eng.stop()
        return out

    results = asyncio.run(main())
    assert all(len(r) >= 4 for r in results)
    assert eng.fence.post_warmup_compiles == 0, (
        "the zero-compile serving invariant broke: a jitted engine entry "
        "compiled mid-serving (run with jax_log_compiles to locate it)")
    assert eng.stats()["post_warmup_compiles_total"] == 0
    # the engine's dispatch wrapper stamped real step-fn call forms, so
    # any trip above would have named the offending form
    assert eng.fence.last_dispatch_form().split("(")[0] in {
        "prefill_fn", "decode_fn", "decode_multi_fn", "verify_fn",
        "long_prefill_fn"}

    # an intentionally unbucketed call trips warn mode
    eng.fence._mode_override = "warn"
    with caplog.at_level(logging.WARNING, "dynamo_tpu.engine.fence"):
        jax.jit(lambda x: x - 3)(jnp.zeros((11,)))
    assert eng.fence.post_warmup_compiles >= 1
    assert eng.stats()["post_warmup_compiles_total"] >= 1
    assert any("XLA compile after warmup" in r.message
               for r in caplog.records)
    eng.fence.disarm()


def test_warmup_covers_host_tier_programs():
    """With the host tier enabled, warmup compiles the pow2 offload
    gather / restore scatter programs, so the first eviction under load
    never compiles (the dynajit warmup-coverage rule pins the entries;
    this pins the shapes)."""
    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=8, num_pages=16, max_batch=2,
                        prefill_chunk=16, batch_buckets=(1, 2),
                        prefill_buckets=(16,), page_buckets=(4,),
                        decode_steps=1, pipeline_decode=False,
                        host_pages=8)
    eng = JaxEngine(cfg, ecfg, seed=0)
    eng.warmup(decode=False)
    # replay the tier drain's gather/scatter at several distinct batch
    # sizes: each pads to a pow2 the warmup loop already compiled, so
    # the fence stays quiet
    for size in (1, 2, 3, 5):
        idx = jnp.zeros(
            _next_pow2(size), jnp.int32)
        from dynamo_tpu.engine.jax_engine import (_gather_pages,
                                                  _inject_pages)

        g = _gather_pages(eng.kv_k, idx)
        eng.kv_k = _inject_pages(
            eng.kv_k, jnp.full((_next_pow2(size),), ecfg.num_pages,
                               jnp.int32), g)
    assert eng.fence.post_warmup_compiles == 0
    eng.fence.disarm()


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
