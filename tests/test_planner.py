"""Planner v0: pure policy decisions + multi-worker advisory emission
(reference docs/architecture.md:47 — the Planner roadmap component)."""

import asyncio

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.metrics import MockWorker
from dynamo_tpu.planner import (PLANNER_ADVISORY_SUBJECT, ComponentSnapshot,
                                Planner, PlannerConfig, WatchTarget, decide,
                                read_advisories)
from dynamo_tpu.runtime.dcp_client import pack, unpack
from dynamo_tpu.runtime.runtime import DistributedRuntime


def _snap(n, usage=0.5, waiting=0, queue=0):
    metrics = {i: ForwardPassMetrics(gpu_cache_usage_perc=usage,
                                     num_requests_waiting=waiting)
               for i in range(n)}
    return ComponentSnapshot("decode", metrics, queue_depth=queue)


CFG = PlannerConfig(min_replicas=1, max_replicas=8,
                    scale_up_cooldown_s=30.0, scale_down_cooldown_s=180.0)


class TestPolicy:
    def test_steady_state_holds(self):
        assert decide(_snap(2, usage=0.5), CFG, now=0.0) is None

    def test_cache_pressure_scales_up_proportionally(self):
        adv = decide(_snap(2, usage=0.95), CFG, now=0.0)
        assert adv is not None and adv.direction == "up"
        # 0.95/0.85 ≈ 1.12 → ceil(2*1.12) = 3
        assert adv.desired_replicas == 3
        assert "cache usage" in adv.reason

    def test_queue_depth_scales_up(self):
        adv = decide(_snap(2, queue=20), CFG, now=0.0)
        assert adv is not None and adv.direction == "up"
        # queue/worker 10 vs cap 4 → pressure 2.5, capped at 2n
        assert adv.desired_replicas == 4
        assert "queue/worker" in adv.reason

    def test_up_step_clamped_to_max(self):
        cfg = PlannerConfig(max_replicas=3)
        adv = decide(_snap(3, usage=0.99, waiting=50), cfg, now=0.0)
        assert adv is None  # already at max → desired==current → hold

    def test_up_cooldown_suppresses(self):
        adv = decide(_snap(2, usage=0.95), CFG, now=10.0, last_up_at=0.0)
        assert adv is None
        adv = decide(_snap(2, usage=0.95), CFG, now=40.0, last_up_at=0.0)
        assert adv is not None

    def test_scale_down_requires_idle_and_cooldown(self):
        # busy queue blocks down even at low usage
        assert decide(_snap(4, usage=0.1, queue=1), CFG, now=1000.0) is None
        adv = decide(_snap(4, usage=0.1), CFG, now=1000.0)
        assert adv is not None and adv.direction == "down"
        assert adv.desired_replicas == 3  # one at a time
        # inside down-cooldown: hold
        assert decide(_snap(3, usage=0.1), CFG, now=1010.0,
                      last_down_at=1000.0) is None
        # a recent up also blocks down (don't shed what we just added)
        assert decide(_snap(3, usage=0.1), CFG, now=1000.0,
                      last_up_at=900.0) is None

    def test_never_below_min(self):
        assert decide(_snap(1, usage=0.0), CFG, now=1000.0) is None

    def test_zero_replicas_cold_start(self):
        adv = decide(ComponentSnapshot("decode", {}), CFG, now=0.0)
        assert adv is not None
        assert adv.current_replicas == 0
        assert adv.desired_replicas == CFG.min_replicas
        # re-emission is rate-limited by the up-cooldown (no every-tick
        # republish during an outage)
        assert decide(ComponentSnapshot("decode", {}), CFG, now=5.0,
                      last_up_at=0.0) is None


def test_scrape_blackout_never_applies_scale_down(run_async):
    """Zero-observed guard end-to-end: a scrape blackout (no worker
    answers stats → empty metrics, current_replicas == 0) must publish
    at most a cold-start advisory and must NEVER edit the stored
    deployment spec — and once the blackout lifts, normal advisories
    resume and apply again."""

    async def scenario():
        drt = await DistributedRuntime.detached()
        drt2 = await DistributedRuntime.attach(drt.dcp.address)
        # workers whose stats handler fails: registered in discovery but
        # dark on the stats plane — exactly a scrape blackout
        dark = [True]

        def _stats():
            if dark[0]:
                raise RuntimeError("scrape blackout")
            return ForwardPassMetrics(num_requests_waiting=8).to_dict()

        workers = []
        for d in (drt, drt2):
            w = MockWorker(d, component="pool", seed=5,
                           hit_rate_interval=9e9)
            w._stats = _stats
            await w.start()
            workers.append(w)

        spec = {"metadata": {"name": "graph"},
                "spec": {"services": {"pool": {"replicas": 2}}}}
        await drt.dcp.kv_put("deployments/graph", pack(spec))

        fake_now = [100.0]
        planner = Planner(
            drt, "dynamo",
            [WatchTarget(component="pool", deployment="graph",
                         config=PlannerConfig(min_replicas=1,
                                              max_replicas=8))],
            apply=True, clock=lambda: fake_now[0],
            wall_clock=lambda: fake_now[0])
        await planner.start(run_loop=False)

        # blackout tick: empty metrics → cold-start advisory published…
        advs_blackout = await planner.tick()
        spec_after_blackout = unpack(
            await drt.dcp.kv_get("deployments/graph"))

        # …and re-emission is cooldown-rate-limited during the outage
        fake_now[0] = 105.0
        advs_repeat = await planner.tick()

        # blackout lifts: waiting pressure resumes normal advisories,
        # which DO apply to the stored spec again
        dark[0] = False
        fake_now[0] = 200.0
        advs_after = await planner.tick()
        spec_after_recover = unpack(
            await drt.dcp.kv_get("deployments/graph"))

        await planner.stop()
        for w in workers:
            await w.stop()
        await drt2.shutdown()
        await drt.shutdown()
        return (advs_blackout, advs_repeat, advs_after,
                spec_after_blackout, spec_after_recover)

    (blackout, repeat, after, spec_blackout, spec_recover) = \
        run_async(scenario())
    # blackout: advisory emitted (cold-start shape), at the virtual time
    assert len(blackout) == 1
    assert blackout[0].current_replicas == 0
    assert blackout[0].desired_replicas == 1
    assert blackout[0].at == 100.0   # wall_clock hook, not time.time()
    # …but the stored spec was NOT auto-applied (guard)
    assert spec_blackout["spec"]["services"]["pool"]["replicas"] == 2
    # cooldown suppresses re-publication while still dark
    assert repeat == []
    # recovery: both workers answer with 8 waiting each → scale-up that
    # applies to the spec again
    assert len(after) == 1 and after[0].direction == "up"
    assert after[0].current_replicas == 2
    assert spec_recover["spec"]["services"]["pool"]["replicas"] == \
        after[0].desired_replicas


def test_planner_start_waits_down_cooldown(run_async):
    """Startup hysteresis: a fresh planner's first look at an idle pool
    must not shed a replica — scale-down is gated on a full down-cooldown
    from start; scale-up stays immediate."""

    async def scenario():
        drt = await DistributedRuntime.detached()
        drt2 = await DistributedRuntime.attach(drt.dcp.address)
        workers = [MockWorker(d, component="pool", seed=11,
                              hit_rate_interval=9e9,
                              profile=lambda tick: ForwardPassMetrics(
                                  gpu_cache_usage_perc=0.01))
                   for d in (drt, drt2)]
        for w in workers:
            await w.start()

        fake_now = [50.0]
        cfg = PlannerConfig(min_replicas=1, max_replicas=8,
                            scale_down_cooldown_s=180.0)
        planner = Planner(drt, "dynamo",
                          [WatchTarget(component="pool", config=cfg)],
                          clock=lambda: fake_now[0],
                          wall_clock=lambda: fake_now[0])
        await planner.start(run_loop=False)
        idle_first = await planner.tick()      # inside startup cooldown
        fake_now[0] = 50.0 + 181.0
        idle_later = await planner.tick()      # cooldown elapsed

        await planner.stop()
        for w in workers:
            await w.stop()
        await drt2.shutdown()
        await drt.shutdown()
        return idle_first, idle_later

    idle_first, idle_later = run_async(scenario())
    assert idle_first == []                    # no knee-jerk shed
    assert len(idle_later) == 1
    assert idle_later[0].direction == "down"   # but downs still work


def test_planner_emits_and_applies(run_async):
    """Two live mock workers + a deep queue → UP advisory on the bus, in
    KV, and applied to the stored deployment spec (the closed loop the
    K8s controller converges)."""

    async def scenario():
        drt = await DistributedRuntime.detached()
        # two workers in the pool: separate runtimes → separate instance
        # ids on the stats plane
        drt2 = await DistributedRuntime.attach(drt.dcp.address)
        workers = [MockWorker(d, component="pool", seed=7,
                              hit_rate_interval=9e9) for d in (drt, drt2)]
        for w in workers:
            await w.start()

        # deep shared queue: 20 items over 2 workers >> cap 4
        for i in range(20):
            await drt.dcp.queue_put("dynamo.pq", pack({"i": i}))

        # stored deployment spec the --apply path edits
        spec = {"metadata": {"name": "graph"},
                "spec": {"services": {"pool": {"replicas": 2}}}}
        await drt.dcp.kv_put("deployments/graph", pack(spec))

        heard = []

        async def on_adv(msg):
            heard.append(unpack(msg.payload))

        await drt.dcp.subscribe(
            f"dynamo.{PLANNER_ADVISORY_SUBJECT}", on_adv)

        fake_now = [0.0]
        planner = Planner(
            drt, "dynamo",
            [WatchTarget(component="pool", queue="pq",
                         deployment="graph",
                         config=PlannerConfig(max_replicas=8))],
            apply=True, clock=lambda: fake_now[0])
        await planner.start()
        planner._task.cancel()  # drive ticks manually for determinism

        advs = await planner.tick()
        assert len(advs) == 1 and advs[0].direction == "up"
        # cooldown: immediate second tick emits nothing
        fake_now[0] = 5.0
        assert await planner.tick() == []

        await asyncio.sleep(0.1)  # let the pub-sub fanout land
        stored = await read_advisories(drt.dcp)
        new_spec = unpack(await drt.dcp.kv_get("deployments/graph"))

        await planner.stop()
        for w in workers:
            await w.stop()
        await drt2.shutdown()
        await drt.shutdown()
        return advs, heard, stored, new_spec

    advs, heard, stored, new_spec = run_async(scenario())
    adv = advs[0]
    assert adv.current_replicas == 2 and adv.desired_replicas == 4
    assert heard and heard[0]["component"] == "pool"
    assert stored and stored[0]["desired_replicas"] == 4
    assert new_spec["spec"]["services"]["pool"]["replicas"] == 4


def test_elastic_loop_end_to_end(run_async):
    """The full elastic-scaling loop: planner --apply edits the stored
    deployment spec (CAS in the control-plane KV) → the same spec renders
    as a DynamoDeployment CR → the K8s reconcile controller converges the
    fake cluster's Deployment to the advised replica count. Decide
    (planner) and actuate (controller) meet in the middle."""
    from tests.test_k8s_controller import FakeKube
    from dynamo_tpu.k8s.controller import Reconciler
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    async def scenario():
        drt = await DistributedRuntime.detached()
        drt2 = await DistributedRuntime.attach(drt.dcp.address)
        workers = [MockWorker(d, component="pool", seed=3,
                              hit_rate_interval=9e9) for d in (drt, drt2)]
        for w in workers:
            await w.start()
        for i in range(30):  # deep queue → scale-up pressure
            await drt.dcp.queue_put("dynamo.pq", pack({"i": i}))

        cr = {"apiVersion": "dynamo-tpu.dev/v1alpha1",
              "kind": "DynamoDeployment",
              "metadata": {"name": "graph", "namespace": "serving",
                           "uid": "u1"},
              "spec": {"graph": "examples.llm.graphs.agg:Frontend",
                       "services": {"pool": {"replicas": 2}}}}
        await drt.dcp.kv_put("deployments/graph", pack(cr))

        planner = Planner(
            drt, "dynamo",
            [WatchTarget(component="pool", queue="pq", deployment="graph",
                         service="pool",
                         config=PlannerConfig(max_replicas=8))],
            apply=True, clock=lambda: 0.0)
        await planner.start()
        planner._task.cancel()
        advs = await planner.tick()
        await planner.stop()
        new_cr = unpack(await drt.dcp.kv_get("deployments/graph"))

        for w in workers:
            await w.stop()
        await drt2.shutdown()
        await drt.shutdown()
        return advs, new_cr

    advs, new_cr = run_async(scenario())
    assert advs and advs[0].direction == "up"
    desired = advs[0].desired_replicas
    assert new_cr["spec"]["services"]["pool"]["replicas"] == desired

    # the spec the planner wrote IS a CR the controller converges
    kube = FakeKube()
    kube.create("DynamoDeployment", "serving", new_cr)
    Reconciler(kube).reconcile_all("serving")
    dep = kube.get("Deployment", "serving", "graph-pool")
    assert dep is not None
    assert dep["spec"]["replicas"] == desired
