"""Tensor-parallel serving: the Pallas decode kernel survives TP via
shard_map (VERDICT r2 weak #5 — r2 silently dropped the kernel whenever
mesh.size > 1), and the multi-host bootstrap is launchable end-to-end.

Reference parity: vLLM multi-node TP rode a Ray head/follower bootstrap
(lib/llm/src/engines/vllm/ray.rs); here every process runs the same
`dynamo-run` command with --coordinator/--num-processes/--process-id and
jax.distributed forms the global mesh (SURVEY §5 comm backend).
"""

import socket
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.parallel.mesh import (MeshSpec, shard_batch, shard_kv_cache,
                                      shard_params)


def _window_args(cfg, params, kv_k, kv_v, B, P, E=4):
    table = np.zeros((B, P), np.int32)
    # distinct pages per row (page 0 reserved)
    for b in range(B):
        table[b] = np.arange(1 + b * P, 1 + (b + 1) * P)
    start = np.full(B, 9, np.int32)  # some pool context
    return dict(
        tokens=jnp.asarray(np.arange(1, B + 1, dtype=np.int32)),
        positions=jnp.asarray(start),
        done=jnp.zeros(B, bool),
        steps=jnp.zeros(B, jnp.int32),
        remaining=jnp.full(B, 100, jnp.int32),
        kv_k=kv_k, kv_v=kv_v,
        page_table=jnp.asarray(table),
        temperature=jnp.zeros(B),
        top_k=jnp.zeros(B, jnp.int32),
        top_p=jnp.ones(B),
        seeds=jnp.zeros(B, jnp.uint32),
        eos_table=jnp.full((B, E), -1, jnp.int32),
    )


def test_sharded_window_kernel_matches_unsharded():
    """Fused decode window with the kernel shard_map'd over (data, model)
    axes == the unsharded XLA window, token-for-token (greedy)."""
    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=64,
                           hidden_size=64, vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    spec = llama.KVCacheSpec(num_pages=64, page_size=4)
    B, P, K = 4, 4, 3

    # seed the pool with real prefill content so attention has context
    def prefill(kv_k, kv_v):
        pre, _ = llama.make_step_fns(cfg, allow_pallas=False)
        T = 12
        toks = jnp.asarray(np.tile(np.arange(2, T + 2, dtype=np.int32)[None],
                                   (B, 1)))
        pos = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
        table = np.zeros((B, P), np.int32)
        for b in range(B):
            table[b] = np.arange(1 + b * P, 1 + (b + 1) * P)
        slots = np.zeros((B, T), np.int32)
        for b in range(B):
            posn = np.arange(T)
            slots[b] = table[b][posn // 4] * 4 + posn % 4
        lg, kv_k, kv_v = pre(params, toks, pos, kv_k, kv_v,
                             jnp.asarray(table), jnp.asarray(slots),
                             jnp.full(B, T - 1, jnp.int32))
        return kv_k, kv_v

    # unsharded XLA reference
    kv_k, kv_v = llama.init_kv_cache(cfg, spec)
    kv_k, kv_v = prefill(kv_k, kv_v)
    ref_fn = llama.make_decode_window_fn(cfg, allow_pallas=False)
    a = _window_args(cfg, params, kv_k, kv_v, B, P)
    ref_toks, _, ref_carry, _, _ = ref_fn(
        params, a["tokens"], a["positions"], a["done"], a["steps"],
        a["remaining"], a["kv_k"], a["kv_v"], a["page_table"],
        a["temperature"], a["top_k"], a["top_p"], a["seeds"],
        a["eos_table"], k_steps=K)

    # sharded kernel path (interpret mode) on a data=2 x model=2 mesh
    mesh = MeshSpec(data=2, model=2).build()
    kv_k2, kv_v2 = llama.init_kv_cache(cfg, spec)
    kv_k2, kv_v2 = prefill(kv_k2, kv_v2)
    kv_k2, kv_v2 = shard_kv_cache(kv_k2, kv_v2, cfg, mesh)
    sp = shard_params(params, cfg, mesh)
    tp_fn = llama.make_decode_window_fn(cfg, allow_pallas=True, mesh=mesh,
                                        pallas_interpret=True)
    a = _window_args(cfg, sp, kv_k2, kv_v2, B, P)
    sb = shard_batch(mesh, tokens=a["tokens"], positions=a["positions"],
                     page_table=a["page_table"])
    got_toks, _, got_carry, _, _ = tp_fn(
        sp, sb["tokens"], sb["positions"], a["done"], a["steps"],
        a["remaining"], kv_k2, kv_v2, sb["page_table"],
        a["temperature"], a["top_k"], a["top_p"], a["seeds"],
        a["eos_table"], k_steps=K)

    np.testing.assert_array_equal(np.asarray(got_toks), np.asarray(ref_toks))
    np.testing.assert_array_equal(np.asarray(got_carry[1]),
                                  np.asarray(ref_carry[1]))  # positions


MULTIHOST_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dynamo_tpu.parallel.mesh import initialize_multihost
    coord, pid = sys.argv[1], int(sys.argv[2])
    initialize_multihost(coord, 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np
    mesh = Mesh(np.asarray(jax.devices()).reshape(2), ("model",))
    x = jax.make_array_from_callback(
        (2,), NamedSharding(mesh, P("model")),
        lambda idx: np.ones((1,), np.float32))
    y = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))(x)
    assert float(y) == 2.0, float(y)
    print("MULTIHOST_OK", pid, flush=True)
""")


def test_multihost_two_process_smoke(tmp_path):
    """Two real processes join via initialize_multihost (the Ray-bootstrap
    replacement) and run a jitted collective over the global 2-device CPU
    mesh. Environment assembly rides the shared forced-device-count
    harness (tests/device_harness.py): devices=1 strips XLA_FLAGS so each
    process contributes exactly one CPU device."""
    from device_harness import forced_device_env

    script = tmp_path / "worker.py"
    script.write_text(MULTIHOST_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = forced_device_env(devices=1)
    procs = [subprocess.Popen([sys.executable, str(script), coord, str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=100)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"MULTIHOST_OK {i}" in out


def test_long_prompt_takes_ring_path(run_async):
    """Serving wire-up of the sequence-parallel prefill (VERDICT r2 item
    5): a prompt above long_prefill_threshold is prefetched through
    make_long_prefill_fn on the seq-axis mesh — and the continuation is
    token-identical to the ordinary chunked-prefill engine."""
    import asyncio

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=8,
                           hidden_size=32, vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(1))
    prompt = [(i * 13) % 200 + 1 for i in range(40)]

    async def gen(engine):
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    base_ecfg = dict(page_size=4, num_pages=64, max_batch=4,
                     prefill_chunk=32, prefill_buckets=(32,),
                     batch_buckets=(4,), page_buckets=(16,))
    want = run_async(gen(JaxEngine(cfg, EngineConfig(**base_ecfg),
                                   params=params)))

    mesh = MeshSpec(seq=4).build()
    engine = JaxEngine(cfg, EngineConfig(long_prefill_threshold=16,
                                         **base_ecfg),
                       params=params, mesh=mesh)
    got = run_async(gen(engine))
    assert engine.long_prefills_total == 1, "ring path not taken"
    assert engine.stats()["long_prefills_total"] == 1
    assert got == want
    # short prompts still take the chunked path
    engine2 = JaxEngine(cfg, EngineConfig(long_prefill_threshold=16,
                                          **base_ecfg),
                        params=params, mesh=mesh)

    async def gen_short(engine):
        req = PreprocessedRequest(
            token_ids=list(prompt[:10]), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    run_async(gen_short(engine2))
    assert engine2.long_prefills_total == 0


def _prefill_inputs(B, P, T, ps):
    """Shared prefill batch: distinct pages per row (page 0 reserved)."""
    toks = np.tile(np.arange(2, T + 2, dtype=np.int32)[None], (B, 1))
    pos = np.tile(np.arange(T, dtype=np.int32)[None], (B, 1))
    table = np.zeros((B, P), np.int32)
    slots = np.zeros((B, T), np.int32)
    for b in range(B):
        table[b] = np.arange(1 + b * P, 1 + (b + 1) * P)
        posn = np.arange(T)
        slots[b] = table[b][posn // ps] * ps + posn % ps
    return (jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(table),
            jnp.asarray(slots), jnp.full(B, T - 1, jnp.int32))


def test_sharded_prefill_kernel_matches_unsharded(monkeypatch):
    """Flash prefill kernel under TP (VERDICT r3 task 5): prefill_step on
    a data=2 x model=2 mesh routes through
    paged_attention_prefill_sharded (interpret mode) and its logits + KV
    pool writes match the unsharded XLA gather path."""
    monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("DYN_PREFILL_PALLAS", "1")
    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=64,
                           hidden_size=64, vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    spec = llama.KVCacheSpec(num_pages=64, page_size=4)
    B, P, T = 4, 4, 12
    toks, pos, table, slots, last = _prefill_inputs(B, P, T, 4)

    kv_k, kv_v = llama.init_kv_cache(cfg, spec)
    pre_ref, _ = llama.make_step_fns(cfg, allow_pallas=False)
    lg_ref, kv_k_ref, kv_v_ref = pre_ref(params, toks, pos, kv_k, kv_v,
                                         table, slots, last)

    mesh = MeshSpec(data=2, model=2).build()
    sp = shard_params(params, cfg, mesh)
    kv_k2, kv_v2 = shard_kv_cache(*llama.init_kv_cache(cfg, spec), cfg, mesh)
    pre_tp, _ = llama.make_step_fns(cfg, mesh=mesh)
    sb = shard_batch(mesh, tokens=toks, positions=pos, page_table=table,
                     flat_slots=slots, last_idx=last)
    lg_tp, kv_k_tp, kv_v_tp = pre_tp(sp, sb["tokens"], sb["positions"],
                                     kv_k2, kv_v2, sb["page_table"],
                                     sb["flat_slots"], sb["last_idx"])

    np.testing.assert_allclose(np.asarray(lg_tp), np.asarray(lg_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(kv_k_tp), np.asarray(kv_k_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(kv_v_tp), np.asarray(kv_v_ref),
                               rtol=2e-5, atol=2e-5)


def test_sharded_k1_decode_kernel_matches_unsharded(monkeypatch):
    """K=1 decode kernel under TP (VERDICT r3 task 5): decode_step on a
    data=2 x model=2 mesh routes through paged_attention_decode_sharded
    (interpret mode) and matches the unsharded XLA path."""
    monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=64,
                           hidden_size=64, vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    spec = llama.KVCacheSpec(num_pages=64, page_size=4)
    B, P, T = 4, 4, 12
    toks, pos, table, slots, last = _prefill_inputs(B, P, T, 4)

    def seed(kv):
        pre, _ = llama.make_step_fns(cfg, allow_pallas=False)
        _, k, v = pre(params, toks, pos, *kv, table, slots, last)
        return k, v

    d_toks = jnp.asarray(np.arange(5, 5 + B, dtype=np.int32))
    d_pos = jnp.full(B, T, jnp.int32)
    d_slots = jnp.asarray(np.asarray(table)[:, T // 4] * 4 + T % 4,
                          jnp.int32)

    kv_ref = seed(llama.init_kv_cache(cfg, spec))
    _, dec_ref = llama.make_step_fns(cfg, allow_pallas=False)
    lg_ref, _, _ = dec_ref(params, d_toks, d_pos, *kv_ref, table, d_slots)

    mesh = MeshSpec(data=2, model=2).build()
    sp = shard_params(params, cfg, mesh)
    kv_tp = shard_kv_cache(*seed(llama.init_kv_cache(cfg, spec)), cfg, mesh)
    _, dec_tp = llama.make_step_fns(cfg, mesh=mesh)
    sb = shard_batch(mesh, tokens=d_toks, positions=d_pos, page_table=table,
                     flat_slots=d_slots)
    lg_tp, _, _ = dec_tp(sp, sb["tokens"], sb["positions"], *kv_tp,
                         sb["page_table"], sb["flat_slots"])

    np.testing.assert_allclose(np.asarray(lg_tp), np.asarray(lg_ref),
                               rtol=2e-5, atol=2e-5)


def test_sharded_window_kernel_gemma2_matches_xla(monkeypatch):
    """The sharded pool+window kernel path with Gemma-2 semantics (score
    softcap + sliding window with its per-row lower bound crossing shard_map
    as a new operand) is token-identical to the unsharded XLA window."""
    monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=64,
                           hidden_size=64, vocab_size=256,
                           model_type="gemma2", sandwich_norms=True,
                           attn_logit_softcap=20.0, sliding_window=6,
                           query_pre_attn_scalar=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(2))
    spec = llama.KVCacheSpec(num_pages=64, page_size=4)
    B, P, K = 4, 4, 3

    def prefill(kv_k, kv_v):
        pre, _ = llama.make_step_fns(cfg, allow_pallas=False)
        T = 12
        toks = jnp.asarray(np.tile(np.arange(2, T + 2, dtype=np.int32)[None],
                                   (B, 1)))
        pos = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
        table = np.zeros((B, P), np.int32)
        for b in range(B):
            table[b] = np.arange(1 + b * P, 1 + (b + 1) * P)
        slots = np.zeros((B, T), np.int32)
        for b in range(B):
            posn = np.arange(T)
            slots[b] = table[b][posn // 4] * 4 + posn % 4
        _, kv_k, kv_v = pre(params, toks, pos, kv_k, kv_v,
                            jnp.asarray(table), jnp.asarray(slots),
                            jnp.full(B, T - 1, jnp.int32))
        return kv_k, kv_v

    monkeypatch.setenv("DYN_DISABLE_PALLAS", "1")  # XLA reference window
    kv_k, kv_v = prefill(*llama.init_kv_cache(cfg, spec))
    ref_fn = llama.make_decode_window_fn(cfg, allow_pallas=False)
    a = _window_args(cfg, params, kv_k, kv_v, B, P)
    ref_toks, _, ref_carry, _, _ = ref_fn(
        params, a["tokens"], a["positions"], a["done"], a["steps"],
        a["remaining"], a["kv_k"], a["kv_v"], a["page_table"],
        a["temperature"], a["top_k"], a["top_p"], a["seeds"],
        a["eos_table"], k_steps=K)
    monkeypatch.delenv("DYN_DISABLE_PALLAS")

    mesh = MeshSpec(data=2, model=2).build()
    kv_k2, kv_v2 = prefill(*llama.init_kv_cache(cfg, spec))
    kv_k2, kv_v2 = shard_kv_cache(kv_k2, kv_v2, cfg, mesh)
    sp = shard_params(params, cfg, mesh)
    tp_fn = llama.make_decode_window_fn(cfg, allow_pallas=True, mesh=mesh,
                                        pallas_interpret=True)
    a = _window_args(cfg, sp, kv_k2, kv_v2, B, P)
    sb = shard_batch(mesh, tokens=a["tokens"], positions=a["positions"],
                     page_table=a["page_table"])
    got_toks, _, got_carry, _, _ = tp_fn(
        sp, sb["tokens"], sb["positions"], a["done"], a["steps"],
        a["remaining"], kv_k2, kv_v2, sb["page_table"],
        a["temperature"], a["top_k"], a["top_p"], a["seeds"],
        a["eos_table"], k_steps=K)

    np.testing.assert_array_equal(np.asarray(got_toks), np.asarray(ref_toks))
    np.testing.assert_array_equal(np.asarray(got_carry[1]),
                                  np.asarray(ref_carry[1]))
