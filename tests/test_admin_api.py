"""Admin REST API (reference deploy/dynamo/api-server): models,
instances, deployments CRUD over the control plane."""

import socket

from dynamo_tpu.admin import AdminApiServer
from dynamo_tpu.runtime.runtime import DistributedRuntime


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_admin_api_crud(run_async):
    port = _free_port()

    async def scenario():
        import aiohttp

        drt = await DistributedRuntime.detached()
        # something to observe: a served endpoint instance
        async def handler(req, ctx):
            yield req

        comp = drt.namespace("ns").component("comp")
        await comp.create_service()
        handle = await comp.endpoint("generate").serve(handler)

        srv = AdminApiServer(drt)
        await srv.start("127.0.0.1", port)
        base = f"http://127.0.0.1:{port}"
        out = {}
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/healthz") as r:
                out["health"] = await r.json()
            async with s.post(f"{base}/api/v1/models", json={
                    "name": "m1", "endpoint": "dyn://ns.comp.generate"}) as r:
                assert r.status == 200
            async with s.get(f"{base}/api/v1/models") as r:
                out["models"] = await r.json()
            async with s.get(f"{base}/api/v1/instances") as r:
                out["instances"] = await r.json()
            async with s.get(f"{base}/api/v1/services") as r:
                out["services"] = await r.json()
            dep = {"metadata": {"name": "d1"},
                   "spec": {"graph": "examples.llm.graphs.agg:Frontend"}}
            async with s.post(f"{base}/api/v1/deployments", json=dep) as r:
                assert r.status == 200
            async with s.get(f"{base}/api/v1/deployments/d1") as r:
                out["dep"] = await r.json()
            async with s.delete(f"{base}/api/v1/deployments/d1") as r:
                assert r.status == 200
            async with s.get(f"{base}/api/v1/deployments/d1") as r:
                out["dep_gone"] = r.status
            async with s.delete(f"{base}/api/v1/models/chat/m1") as r:
                assert r.status == 200
            from dynamo_tpu.planner.policy import PLANNER_KV_PREFIX
            from dynamo_tpu.runtime.dcp_client import pack
            await drt.dcp.kv_put(f"{PLANNER_KV_PREFIX}decode", pack(
                {"component": "decode", "current_replicas": 1,
                 "desired_replicas": 2, "reason": "test", "at": 1.0}))
            async with s.get(f"{base}/api/v1/planner/advisories") as r:
                out["advisories"] = await r.json()
        await srv.stop()
        await handle.stop()
        await drt.shutdown()
        return out

    out = run_async(scenario())
    assert out["health"]["ok"]
    assert out["models"]["models"][0]["name"] == "m1"
    assert any(i["component"] == "comp" for i in
               out["instances"]["instances"])
    assert any(s["component"] == "comp" for s in
               out["services"]["services"])
    assert out["dep"]["spec"]["graph"].startswith("examples.")
    assert out["dep_gone"] == 404
    assert out["advisories"]["advisories"][0]["component"] == "decode"


def test_admin_api_auth_scoping(run_async):
    """Bearer-token multi-tenancy (reference api-server's users/orgs
    plane): 401 without a token, reader is GET-only, a namespace-scoped
    writer mutates only its namespace (and cannot overwrite another
    namespace's spec under the same name), admin does everything."""
    port = _free_port()

    async def scenario():
        import aiohttp

        drt = await DistributedRuntime.detached()
        srv = AdminApiServer(drt, tokens=[
            {"token": "adm", "label": "root", "role": "admin"},
            {"token": "rd", "label": "viewer", "role": "reader"},
            {"token": "wr-a", "label": "team-a", "role": "writer",
             "namespace": "team-a"},
        ])
        await srv.start("127.0.0.1", port)
        base = f"http://127.0.0.1:{port}"

        def hdr(tok=None):
            return {"Authorization": f"Bearer {tok}"} if tok else {}

        dep = {"metadata": {"name": "d1", "namespace": "team-a"},
               "spec": {"graph": "g"}}
        dep_b = {"metadata": {"name": "d2", "namespace": "team-b"},
                 "spec": {"graph": "g"}}
        out = {}
        async with aiohttp.ClientSession() as s:
            # healthz stays open; everything else 401s without a token
            async with s.get(f"{base}/healthz") as r:
                out["health"] = r.status
            async with s.get(f"{base}/api/v1/models") as r:
                out["no_token"] = r.status
            async with s.get(f"{base}/api/v1/models",
                             headers=hdr("bogus")) as r:
                out["bad_token"] = r.status
            # reader: GET ok, POST 403
            async with s.get(f"{base}/api/v1/deployments",
                             headers=hdr("rd")) as r:
                out["reader_get"] = r.status
            async with s.post(f"{base}/api/v1/deployments", json=dep,
                              headers=hdr("rd")) as r:
                out["reader_post"] = r.status
            # scoped writer: own namespace ok, other namespace 403,
            # global models 403
            async with s.post(f"{base}/api/v1/deployments", json=dep,
                              headers=hdr("wr-a")) as r:
                out["writer_own"] = r.status
            async with s.post(f"{base}/api/v1/deployments", json=dep_b,
                              headers=hdr("wr-a")) as r:
                out["writer_other"] = r.status
            async with s.post(f"{base}/api/v1/models",
                              json={"name": "m", "endpoint": "e"},
                              headers=hdr("wr-a")) as r:
                out["writer_models"] = r.status
            # admin stores a team-b spec named d1? No — d1 belongs to
            # team-a; admin CAN overwrite, but team-a's writer must not
            # be able to hijack a team-b spec via rename
            async with s.post(f"{base}/api/v1/deployments", json=dep_b,
                              headers=hdr("adm")) as r:
                out["admin_post"] = r.status
            hijack = {"metadata": {"name": "d2", "namespace": "team-a"},
                      "spec": {"graph": "evil"}}
            async with s.post(f"{base}/api/v1/deployments", json=hijack,
                              headers=hdr("wr-a")) as r:
                out["writer_hijack"] = r.status
            async with s.delete(f"{base}/api/v1/deployments/d2",
                                headers=hdr("wr-a")) as r:
                out["writer_del_other"] = r.status
            async with s.delete(f"{base}/api/v1/deployments/d1",
                                headers=hdr("wr-a")) as r:
                out["writer_del_own"] = r.status
        await srv.stop()
        await drt.shutdown()
        return out

    out = run_async(scenario())
    assert out["health"] == 200
    assert out["no_token"] == 401 and out["bad_token"] == 401
    assert out["reader_get"] == 200 and out["reader_post"] == 403
    assert out["writer_own"] == 200
    assert out["writer_other"] == 403
    assert out["writer_models"] == 403
    assert out["admin_post"] == 200
    assert out["writer_hijack"] == 403  # d2 lives in team-b
    assert out["writer_del_other"] == 403
    assert out["writer_del_own"] == 200


def test_admin_api_rejects_bad_role():
    import pytest

    with pytest.raises(ValueError, match="role"):
        AdminApiServer(None, tokens=[{"token": "x", "role": "root"}])


def test_admin_api_empty_token_list_fails_closed(run_async):
    """tokens=[] means auth CONFIGURED with no valid credentials (a
    templated file whose values were unset) — must 401 everything, not
    silently fail open; and a lowercase 'bearer' scheme is accepted
    (RFC 7235 case-insensitive)."""
    port = _free_port()

    async def scenario():
        import aiohttp

        drt = await DistributedRuntime.detached()
        closed = AdminApiServer(drt, tokens=[])
        await closed.start("127.0.0.1", port)
        base = f"http://127.0.0.1:{port}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/api/v1/models") as r:
                st_closed = r.status
        await closed.stop()

        port2 = _free_port()
        srv = AdminApiServer(drt, tokens=[
            {"token": "t", "label": "x", "role": "reader"}])
        await srv.start("127.0.0.1", port2)
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port2}/api/v1/models",
                             headers={"Authorization": "bearer t"}) as r:
                st_lower = r.status
        await srv.stop()
        await drt.shutdown()
        return st_closed, st_lower

    st_closed, st_lower = run_async(scenario())
    assert st_closed == 401
    assert st_lower == 200


def test_admin_api_rejects_missing_token_field():
    import pytest

    with pytest.raises(ValueError, match="missing 'token'"):
        AdminApiServer(None, tokens=[{"label": "ci", "role": "writer"}])
