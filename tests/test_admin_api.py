"""Admin REST API (reference deploy/dynamo/api-server): models,
instances, deployments CRUD over the control plane."""

import socket

from dynamo_tpu.admin import AdminApiServer
from dynamo_tpu.runtime.runtime import DistributedRuntime


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_admin_api_crud(run_async):
    port = _free_port()

    async def scenario():
        import aiohttp

        drt = await DistributedRuntime.detached()
        # something to observe: a served endpoint instance
        async def handler(req, ctx):
            yield req

        comp = drt.namespace("ns").component("comp")
        await comp.create_service()
        handle = await comp.endpoint("generate").serve(handler)

        srv = AdminApiServer(drt)
        await srv.start("127.0.0.1", port)
        base = f"http://127.0.0.1:{port}"
        out = {}
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/healthz") as r:
                out["health"] = await r.json()
            async with s.post(f"{base}/api/v1/models", json={
                    "name": "m1", "endpoint": "dyn://ns.comp.generate"}) as r:
                assert r.status == 200
            async with s.get(f"{base}/api/v1/models") as r:
                out["models"] = await r.json()
            async with s.get(f"{base}/api/v1/instances") as r:
                out["instances"] = await r.json()
            async with s.get(f"{base}/api/v1/services") as r:
                out["services"] = await r.json()
            dep = {"metadata": {"name": "d1"},
                   "spec": {"graph": "examples.llm.graphs.agg:Frontend"}}
            async with s.post(f"{base}/api/v1/deployments", json=dep) as r:
                assert r.status == 200
            async with s.get(f"{base}/api/v1/deployments/d1") as r:
                out["dep"] = await r.json()
            async with s.delete(f"{base}/api/v1/deployments/d1") as r:
                assert r.status == 200
            async with s.get(f"{base}/api/v1/deployments/d1") as r:
                out["dep_gone"] = r.status
            async with s.delete(f"{base}/api/v1/models/chat/m1") as r:
                assert r.status == 200
            from dynamo_tpu.planner.policy import PLANNER_KV_PREFIX
            from dynamo_tpu.runtime.dcp_client import pack
            await drt.dcp.kv_put(f"{PLANNER_KV_PREFIX}decode", pack(
                {"component": "decode", "current_replicas": 1,
                 "desired_replicas": 2, "reason": "test", "at": 1.0}))
            async with s.get(f"{base}/api/v1/planner/advisories") as r:
                out["advisories"] = await r.json()
        await srv.stop()
        await handle.stop()
        await drt.shutdown()
        return out

    out = run_async(scenario())
    assert out["health"]["ok"]
    assert out["models"]["models"][0]["name"] == "m1"
    assert any(i["component"] == "comp" for i in
               out["instances"]["instances"])
    assert any(s["component"] == "comp" for s in
               out["services"]["services"])
    assert out["dep"]["spec"]["graph"].startswith("examples.")
    assert out["dep_gone"] == 404
    assert out["advisories"]["advisories"][0]["component"] == "decode"
