"""Disaggregated prefill/decode: router decision, queue, KV page transfer,
end-to-end remote prefill matching local generation exactly.

Mirrors the reference's CI strategy (SURVEY §4): everything on CPU JAX,
two engines in one process connected through a real DCP server + real TCP
transfer sockets — the same planes used across hosts.
"""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.disagg import (DisaggRouter, PrefillQueue, PrefillWorker,
                                   RemotePrefillRequest)
from dynamo_tpu.llm.disagg.decode import build_disagg_decode
from dynamo_tpu.llm.disagg.router import publish_config
from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                             SamplingOptions, StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import init_params
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.runtime import DistributedRuntime

PS = 8  # page size for tests


def tiny_cfg():
    return ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=8,
                            hidden_size=32, vocab_size=128)


def make_engine(params=None):
    ecfg = EngineConfig(page_size=PS, num_pages=64, max_batch=4,
                        prefill_chunk=32, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8, 32), page_buckets=(8,),
                        watermark_pages=2)
    return JaxEngine(tiny_cfg(), ecfg, params=params)


def greedy_request(tokens, max_tokens=6):
    return PreprocessedRequest(token_ids=tokens,
                               sampling=SamplingOptions(),
                               stop=StopConditions(max_tokens=max_tokens))


async def collect(engine, req, ctx=None):
    toks = []
    async for out in engine.generate(req, ctx or Context()):
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            return toks, out.finish_reason
    return toks, None


def test_router_decision():
    r = DisaggRouter(max_local_prefill_length=100)
    assert r.prefill_remote(500, 0)
    assert not r.prefill_remote(500, 450)          # prefix hit → local
    assert not r.prefill_remote(50, 0)             # short prompt → local
    r2 = DisaggRouter(max_local_prefill_length=100,
                      max_prefill_queue_size=2)
    assert not r2.prefill_remote(500, 0, queue_depth=2)  # saturated queue
    r3 = DisaggRouter(enabled=False)
    assert not r3.prefill_remote(10_000, 0)


def test_router_live_reconfig(run_async):
    async def main():
        drt = await DistributedRuntime.detached()
        try:
            r = DisaggRouter(max_local_prefill_length=100)
            await r.start_watch(drt.dcp, "test", "m")
            await publish_config(drt.dcp, "test", "m",
                                 max_local_prefill_length=5000,
                                 enabled=True)
            await asyncio.sleep(0.2)
            assert r.max_local_prefill_length == 5000
            assert not r.prefill_remote(1000, 0)
            r.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_prefill_queue_roundtrip(run_async):
    async def main():
        drt = await DistributedRuntime.detached()
        try:
            q = PrefillQueue(drt.dcp, "test")
            req = RemotePrefillRequest(request_id="r1", token_ids=[1, 2, 3],
                                       sampling={"temperature": 0.5},
                                       page_ids=[4, 5], skip_pages=1,
                                       engine_id=7)
            await q.put(req)
            assert await q.depth() == 1
            got = await q.pull(timeout=1.0)
            assert got == req
            assert await q.pull(timeout=0.05) is None
        finally:
            await drt.shutdown()

    run_async(main())


def test_extract_inject_roundtrip(run_async):
    """Pages gathered from one engine and scattered into another carry the
    exact KV contents (the NIXL read/write analog)."""

    async def main():
        params = init_params(tiny_cfg(), __import__("jax").random.PRNGKey(1))
        e1, e2 = make_engine(params), make_engine(params)
        prompt = list(range(1, 20))  # 19 tokens → 3 pages of 8
        ctx = Context("x")
        first, pages = await e1.prefill_only(greedy_request(prompt), ctx)
        k, v = await e1.extract_pages(pages)
        assert k.shape[1] == len(pages)
        dst = [10, 11, 12][:len(pages)]
        await e2.inject_pages(dst, k, v)
        k2, v2 = await e2.extract_pages(dst)
        np.testing.assert_array_equal(np.asarray(k, np.float32),
                                      np.asarray(k2, np.float32))
        np.testing.assert_array_equal(np.asarray(v, np.float32),
                                      np.asarray(v2, np.float32))
        await e1.release_pages(pages)
        await e1.stop()
        await e2.stop()

    run_async(main())


@pytest.mark.parametrize("prompt_len", [19, 24])  # partial + exact pages
def test_disagg_end_to_end_matches_local(run_async, prompt_len):
    """Remote-prefill generation is token-identical to a purely local run
    (same params, greedy sampling)."""

    async def main():
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(2))
        drt = await DistributedRuntime.detached()
        prompt = [(i * 7) % 100 + 1 for i in range(prompt_len)]
        try:
            # reference: plain local engine
            local = make_engine(params)
            want, want_fin = await collect(local, greedy_request(prompt))
            await local.stop()

            decode_eng = make_engine(params)
            prefill_eng = make_engine(params)
            router = DisaggRouter(max_local_prefill_length=4)  # force remote
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="test",
                                               router=router,
                                               watch_config=False)
            pw = PrefillWorker(drt, prefill_eng, namespace="test")
            pw.start()

            got, fin = await collect(disagg, greedy_request(prompt))
            assert disagg.remote_prefills == 1
            assert disagg.remote_fallbacks == 0
            assert pw.completed == 1
            assert fin == want_fin
            assert got == want

            # second identical request: decode-side prefix cache now covers
            # leading pages → skip_pages > 0 path; still identical output
            got2, _ = await collect(disagg, greedy_request(prompt))
            assert got2 == want
            assert disagg.remote_prefills + disagg.local_prefills == 2

            await pw.stop()
            await disagg.transfer.stop()
            await prefill_eng.stop()
            await decode_eng.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_disagg_chunked_vs_bulk_token_identity(run_async):
    """Greedy outputs through the remote-prefill path are token-identical
    between bulk mode (chunk_pages=0) and the multi-chunk stream
    (chunk_pages=1 → one frame per page), and both match a local run."""

    async def main():
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(7))
        prompt = [(i * 13) % 90 + 1 for i in range(26)]  # 4 pages of 8

        local = make_engine(params)
        want, _ = await collect(local, greedy_request(prompt))
        await local.stop()

        for chunk_pages in (0, 1):
            drt = await DistributedRuntime.detached()
            try:
                decode_eng = make_engine(params)
                prefill_eng = make_engine(params)
                router = DisaggRouter(max_local_prefill_length=4)
                disagg = await build_disagg_decode(drt, decode_eng,
                                                   namespace="test",
                                                   router=router,
                                                   watch_config=False)
                pw = PrefillWorker(drt, prefill_eng, namespace="test",
                                   chunk_pages=chunk_pages)
                pw.start()
                got, _ = await collect(disagg, greedy_request(prompt))
                assert disagg.remote_prefills == 1, f"cp={chunk_pages}"
                assert disagg.remote_fallbacks == 0, f"cp={chunk_pages}"
                assert got == want, f"cp={chunk_pages} diverged"
                if chunk_pages == 1:
                    # one frame per page actually went over the wire
                    assert disagg.transfer.chunks_ingested >= 4
                    assert pw.xfer.chunks_sent >= 4
                    assert pw.xfer.extract_seconds > 0
                await pw.stop()
                await disagg.transfer.stop()
                await prefill_eng.stop()
                await decode_eng.stop()
            finally:
                await drt.shutdown()

    run_async(main())


def test_prefill_worker_evicts_stale_client_on_decode_restart(run_async):
    """A decode-worker restart invalidates the cached transfer endpoint;
    the prefill worker must evict the stale client, re-resolve from DCP,
    and retry — not fail every subsequent job to that engine."""

    async def main():
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(8))
        drt = await DistributedRuntime.detached()
        prompt = [(i * 5) % 80 + 1 for i in range(20)]
        prompt2 = [(i * 9) % 80 + 3 for i in range(21)]
        try:
            decode_eng = make_engine(params)
            prefill_eng = make_engine(params)
            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="test",
                                               router=router,
                                               watch_config=False)
            pw = PrefillWorker(drt, prefill_eng, namespace="test")
            pw.start()
            got1, _ = await collect(disagg, greedy_request(prompt))
            assert pw.completed == 1

            # "restart" the decode side's listener: new socket, new port,
            # re-registered under the same engine id — the worker's cached
            # client now points at a dead endpoint
            await disagg.transfer.stop()
            await disagg.transfer.start()
            await disagg.transfer.register(drt.dcp, "test", drt.instance_id)

            got2, _ = await collect(disagg, greedy_request(prompt2))
            assert disagg.remote_prefills == 2
            assert disagg.remote_fallbacks == 0
            assert pw.completed == 2 and pw.failed == 0
            assert pw.client_evictions == 1

            await pw.stop()
            await disagg.transfer.stop()
            await prefill_eng.stop()
            await decode_eng.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_disagg_fallback_on_no_prefill_worker(run_async):
    """No prefill worker alive → decode times out and falls back locally."""

    async def main():
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(3))
        drt = await DistributedRuntime.detached()
        prompt = [(i * 3) % 50 + 1 for i in range(20)]
        try:
            local = make_engine(params)
            want, _ = await collect(local, greedy_request(prompt))
            await local.stop()

            decode_eng = make_engine(params)
            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="test",
                                               router=router,
                                               watch_config=False)
            disagg.prefill_timeout = 0.3
            got, _ = await collect(disagg, greedy_request(prompt))
            assert got == want
            assert disagg.remote_fallbacks == 1
            await disagg.transfer.stop()
            await decode_eng.stop()
        finally:
            await drt.shutdown()

    run_async(main())


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_disagg_concurrent_mixed_fallback_completes(run_async):
    """The TPU-bench wedge scenario, deterministic on CPU: many concurrent
    requests racing remote prefills against a SLOW prefill worker under a
    small decode pool, so the run mixes remote successes, timeout
    fallbacks, local prefills, and late KV arrivals (dropped after
    fallback). Every request must complete — a hang here is the disagg
    deadlock the bench watchdog guards against."""

    async def main():
        import jax

        params = init_params(tiny_cfg(), jax.random.PRNGKey(4))
        drt = await DistributedRuntime.detached()
        try:
            # reference outputs from a plain local engine
            local = make_engine(params)
            prompts = [[(i * 11 + j * 3) % 100 + 1 for j in range(16 + i)]
                       for i in range(10)]
            want = []
            for p in prompts:
                toks, _ = await collect(local, greedy_request(p))
                want.append(toks)
            await local.stop()

            # small decode pool: reservations + admissions contend
            decode_ecfg = EngineConfig(
                page_size=PS, num_pages=24, max_batch=4,
                prefill_chunk=32, batch_buckets=(1, 2, 4),
                prefill_buckets=(8, 32), page_buckets=(8,),
                watermark_pages=2)
            decode_eng = JaxEngine(tiny_cfg(), decode_ecfg, params=params)
            prefill_eng = make_engine(params)
            # pre-compile the full bucket grids BEFORE registering the
            # lease-attached transfer endpoint (bench.py's order): warmup
            # blocks the event loop for multiples of the lease TTL, and a
            # stalled keepalive would expire the lease and delete the
            # endpoint — every remote prefill then fails with "no KV
            # transfer endpoint registered"
            decode_eng.warmup()
            prefill_eng.warmup(decode=False)
            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="stress",
                                               router=router,
                                               watch_config=False)
            # max_inflight covers every request so no fast job queues
            # behind a slow one — the fast/slow mix below stays
            # deterministic per request, not ordering-dependent
            pw = PrefillWorker(drt, prefill_eng, namespace="stress",
                               max_inflight=len(prompts) + 1)

            # slow worker: odd-length prompts sleep far past the decode
            # timeout, so their KV lands AFTER the fallback released the
            # reservation (the late-arrival drop path); even-length
            # prompts are handled promptly and succeed remotely
            orig_handle = pw._handle

            async def slow_handle(req):
                if len(req.token_ids) % 2 == 1:
                    await asyncio.sleep(12.0)
                await orig_handle(req)

            pw._handle = slow_handle
            pw.start()

            disagg.prefill_timeout = 5.0

            results = await asyncio.wait_for(
                asyncio.gather(*(collect(disagg, greedy_request(p))
                                 for p in prompts)),
                timeout=120.0)

            for i, ((toks, fin), w) in enumerate(zip(results, want)):
                assert fin in ("length", "stop"), f"req {i}: {fin}"
                assert toks == w, f"req {i} diverged"
            assert disagg.remote_fallbacks > 0, \
                "stress never exercised the fallback path"
            assert disagg.remote_prefills > disagg.remote_fallbacks, \
                "stress never exercised a remote success"

            await pw.stop()
            await disagg.transfer.stop()
            await prefill_eng.stop()
            await decode_eng.stop()
        finally:
            await drt.shutdown()

    run_async(main())
