"""Baseline (ratchet) file handling.

Entries key on ``path::rule-name::scope`` — NOT line numbers — so
unrelated edits never invalidate them. Each line grandfathers ONE
violation instance; repeat the line (or append ``::N``) to allow N in
the same scope. The gate only ratchets down: a new violation anywhere
fails, a baselined one passes, and an entry that no longer matches
anything prints a stale warning so it gets deleted.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence, Tuple

from .analyzer import Violation


def format_entry(v: Violation) -> str:
    return v.baseline_key


def load_baseline(path: str) -> Dict[str, int]:
    """key -> allowed count. Lines: ``path::rule::scope[::N]``; ``#``
    comments and blanks ignored."""
    allowed: Dict[str, int] = {}
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split("::")
            count = 1
            if len(parts) >= 4 and parts[-1].isdigit():
                count = int(parts[-1])
                parts = parts[:-1]
            key = "::".join(parts)
            allowed[key] = allowed.get(key, 0) + count
    return allowed


def apply_baseline(
    violations: Sequence[Violation], allowed: Dict[str, int]
) -> Tuple[List[Violation], List[str]]:
    """Returns (non-baselined violations, stale baseline keys)."""
    found = Counter(v.baseline_key for v in violations)
    budget = dict(allowed)
    fresh: List[Violation] = []
    for v in violations:
        if budget.get(v.baseline_key, 0) > 0:
            budget[v.baseline_key] -= 1
        else:
            fresh.append(v)
    stale = [key for key, n in allowed.items()
             if found.get(key, 0) < n]
    return fresh, stale
