"""The dynalint AST analyzer: six project-specific rules, stdlib-only.

Each rule has a stable code, a kebab-case name (used in suppression
comments and baseline entries, so line-number churn never invalidates
them), and a one-line message. See ``docs/static_analysis.md`` for the
rationale behind each rule and the cleanup it drove.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

# code -> (kebab-name, summary)
RULES: Dict[str, Tuple[str, str]] = {
    "DL001": ("blocking-call-in-async",
              "blocking call inside an async def body stalls the event loop"),
    "DL002": ("fire-and-forget-task",
              "background task result dropped: exceptions vanish and there "
              "is no cancel-join path"),
    "DL003": ("swallowed-loop-error",
              "broad except inside a loop with neither a log call nor a "
              "backoff sleep can spin silently forever"),
    "DL004": ("lock-across-blocking",
              "blocking call or long await while holding a lock serializes "
              "everything behind it"),
    "DL005": ("jax-host-sync-in-hot-path",
              "host sync (block_until_ready / np.asarray / .item / float) "
              "inside an engine step/decode function"),
    "DL006": ("untracked-env-read",
              "os.environ read outside runtime/config.py: route it through "
              "the env registry so the knob is documented"),
    "DL007": ("span-not-closed",
              "tracer.start_span(...) result used without `with` or an "
              "explicit end(): the span never finishes and leaks from "
              "every trace"),
    # DL008-DL010 are the interprocedural dynaflow rules (callgraph.py /
    # dynaflow.py): they need the whole-program view, so analyze_source
    # never emits them — analyze_tree / the CLI does.
    "DL008": ("transitive-blocking-in-async",
              "blocking call reachable from an async def through sync "
              "helpers stalls the event loop just as surely as a direct "
              "one"),
    "DL009": ("wire-field-drift",
              "wire-frame field used at an encode/decode site but absent "
              "from its declared schema in runtime/wire.py (or declared "
              "required yet never read by any decoder)"),
    "DL010": ("undeclared-wire-frame",
              "codec encode/encode_parts call site whose header matches "
              "no registered wire frame: declare it in runtime/wire.py "
              "and anchor the site with wire.checked(...)"),
    "DL011": ("unbounded-await",
              "await on a network primitive (stream read/drain/connect, "
              "queue get, codec decode) with no asyncio.wait_for/"
              "deadline bound: a dead peer wedges this task forever"),
    # DL012-DL014 are the interprocedural dynarace rules (dynarace.py):
    # they need concurrency-root inference over the whole-program call
    # graph, so analyze_source never emits them — analyze_tree does.
    "DL012": ("atomicity-across-await",
              "shared attribute read before an await and written after "
              "it with no re-check and no common lock: a concurrent "
              "task can interleave at the await, so the write clobbers "
              "its update or acts on a stale check (lost update)"),
    "DL013": ("unguarded-concurrent-mutation",
              "shared attribute mutated outside its declared/observed "
              "lock discipline: annotate it `# guarded-by: ...` and "
              "hold the lock, or take the lock at this site"),
    "DL014": ("lock-order-inversion",
              "locks acquired in opposite nesting orders on different "
              "paths: two tasks taking them concurrently can deadlock "
              "the event loop forever"),
    # DL015-DL017 are the dynajit compilation-stability rules
    # (dynajit.py): device-residency + shape-provenance dataflow over the
    # shared call graph, so analyze_source never emits them —
    # analyze_tree does.
    "DL015": ("recompile-hazard",
              "jitted call site whose argument shape or static-arg value "
              "derives from request-varying data without passing through "
              "a bucket helper: each distinct shape/value is one "
              "serve-time XLA compile that stalls every in-flight "
              "request"),
    "DL016": ("donation-discipline",
              "donated buffer used after the donating jit call (invalid "
              "the moment the call dispatches), or a jitted function "
              "overwriting a buffer param in place without donating it "
              "(XLA keeps a second pool-sized copy in HBM)"),
    "DL017": ("implicit-host-transfer",
              "device-resident value flows into a host-transfer sink "
              "(np.asarray / .item() / .tolist() / float / int / bool / "
              "iteration): a hidden device sync the callsite-pattern "
              "DL005 cannot see"),
    "DL018": ("unsampled-profiler-sync",
              "host sync in profiler code with no sample/flag guard: "
              "dynaprof instrumentation must cost nothing when sampling "
              "is off, so every deliberate sync (block_until_ready, "
              "perf_counter-bracketed readback) must sit under an "
              "`if <...sampl.../enabled/active...>:` guard"),
    # DL019-DL021 are the dynaproto lifecycle-protocol rules
    # (dynaproto.py + modelcheck.py): they check anchors and mutation
    # sites against the declared state machines in runtime/proto.py and
    # model-check the declared invariants, so analyze_source never emits
    # them — analyze_tree does.
    "DL019": ("undeclared-transition",
              "protocol-state mutation or anchor that matches no "
              "declared edge of its lifecycle machine in "
              "runtime/proto.py: every transition of a declared state "
              "machine must name the edge it implements"),
    "DL020": ("protocol-coverage",
              "declared protocol edge with no anchoring code site, an "
              "edge out of a terminal state, a transition breaking the "
              "machine's declared lock discipline, or a model-checked "
              "invariant violated in a reachable interleaving"),
    "DL021": ("typed-error-swallow",
              "broad except on an HTTP/ServeHandle-reachable await path "
              "swallows the typed guard errors (DeadlineExceeded, "
              "NoCapacity, NoRespondersError) that must reach the "
              "504/503 mappers — peel them off or re-raise"),
    # DL022-DL024 are the dynahot hot-path cost rules (dynahot.py):
    # hot regions come from callgraph reachability over the declared
    # HOT_ROOTS registry with per-frame loop depth, so analyze_source
    # never emits them — analyze_tree does.
    "DL022": ("hot-loop-invariant-work",
              "loop-invariant work re-done every iteration of a hot "
              "loop (invariant-default rebuild, re.compile/struct/"
              "constant-asarray in loop, sorted() of an invariant, "
              "repeated deep attribute chains, exception-probe loop "
              "discovery) — hoist or cache it once"),
    "DL023": ("hot-eager-format",
              "string formatted eagerly for a logging/trace call on a "
              "hot frame with no level or sampling guard: the format "
              "cost is paid per token even when the sink drops it"),
    "DL024": ("unbounded-growth",
              "self.<attr> collection grows on the request path with no "
              "reachable removal, bound check, ring, or eviction — the "
              "leak class that falls over under sustained churn; cap "
              "it or justify with `# bounded-by: <reason>`"),
    # DL025-DL027 are the dynaform dtype-provenance / call-form rules
    # (dynaform.py): a dtype x provenance lattice over the shared parse
    # and call graph, so analyze_source never emits them — analyze_tree
    # does.
    "DL025": ("silent-dtype-promotion",
              "JAX weak-type promotion widens a bf16/int8 device value "
              "to fp32 on a hot path (fp32 operand or python float into "
              "int8) — 2-4x the bytes/FLOPs of the intended dtype; cast "
              "explicitly or justify with `# promote-ok: <reason>`"),
    "DL026": ("warmup-form-drift",
              "serving-path jitted call form (arity, operand dtype/"
              "committedness, explicit-kwarg set, static kwarg values, "
              "list-convert construction) that warmup() never "
              "exercises: the first serving call in that form pays a "
              "multi-second XLA compile mid-flight"),
    "DL027": ("tier-dtype-contract",
              "int8 host-tier pages consumed without dequantize_pages, "
              "a dequantize missing its scale tensor, a quantize whose "
              "scales are dropped, or an fp16-fallback path touching "
              "int8 scale pools — tier formats must never mix"),
}

NAME_TO_CODE = {name: code for code, (name, _) in RULES.items()}

# ---------------------------------------------------------------- rule config

# DL001/DL004: sync calls that block the calling thread.
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "subprocess.getstatusoutput",
    "os.system", "os.waitpid", "os.wait",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen",
})
BLOCKING_PREFIXES = ("requests.",)
# builtins that do blocking file IO
BLOCKING_BUILTINS = frozenset({"open"})

# DL002: task-spawning calls whose result must be tracked.
SPAWN_CALLS = frozenset({"asyncio.create_task", "asyncio.ensure_future"})
# calls that take ownership of a task passed to them
TRACKING_SINKS = frozenset({
    "asyncio.gather", "asyncio.wait", "asyncio.wait_for", "asyncio.shield",
    "asyncio.as_completed", "cancel_join", "tasks.cancel_join",
})
TRACKING_ATTRS = frozenset({"cancel", "add_done_callback", "result",
                            "exception"})

# DL003: logging-ish method names that count as "the error is surfaced".
LOG_METHODS = frozenset({"debug", "info", "warning", "error", "exception",
                         "critical", "log", "print"})

# DL004: a with-item whose context expression's last segment matches this
# is treated as a lock. Semaphores are deliberately excluded: a
# concurrency cap is SUPPOSED to be held across the long await it gates.
LOCK_NAME_RE = re.compile(r"(?i)(lock|mutex)$")
LONG_AWAIT_CALLS = frozenset({"asyncio.sleep", "asyncio.wait",
                              "asyncio.wait_for", "asyncio.gather"})
LONG_AWAIT_ATTRS = frozenset({"wait", "acquire", "join"})

# DL005: applies to hot-NAMED functions in modules under engine/. The
# name grammar is declared once in the dynahot HOT_ROOTS registry
# ("frame_name_segments") and compiled there as HOT_FRAME_RE — imported
# lazily (dynahot imports this module, so a top-level import would
# cycle) and cached here. Identical to the legacy inline
# `(^|_)step($|_)` regex; the equivalence is pinned by test.
HOT_PATH_MARKERS = ("engine/",)
_HOT_FRAME_RE_CACHE: Optional[re.Pattern] = None


def hot_frame_re() -> re.Pattern:
    global _HOT_FRAME_RE_CACHE
    if _HOT_FRAME_RE_CACHE is None:
        from .dynahot import HOT_FRAME_RE
        _HOT_FRAME_RE_CACHE = HOT_FRAME_RE
    return _HOT_FRAME_RE_CACHE
HOST_SYNC_CALLS = frozenset({"jax.block_until_ready", "np.asarray",
                             "np.array", "numpy.asarray", "numpy.array"})
# Deliberately-synchronous scheduler arms: the sync is the design (the
# spec-decode arm verifies on-host; the single-step fallback is the
# pre-async engine). New step functions do NOT belong here — overlap
# device work instead, or carry an inline disable with a justification.
# Entries are excluded both as hot-path origins (per-file rule) and as
# sanctioned callees/sinks of the interprocedural pass (dynarace
# check_transitive_host_sync), which otherwise reports any host sync a
# *step* function reaches through sync helpers at its call site.
HOT_SYNC_ALLOWLIST = frozenset({
    "JaxEngine._step_spec",
    "JaxEngine._decode_step_spec",
    "JaxEngine._decode_step_single",
    # pipelined-scheduler readback/staging arms (the ROADMAP item 3
    # overhaul targets): _process_window/_process_prefill materialize
    # sampled tokens on host, _dispatch_prefill stages host token
    # buffers for device dispatch, _land_inflight_offloads copies
    # offloaded KV into the host pool. Each is the designed sync point
    # of the dispatch pipeline; any NEW helper a step function reaches
    # still fires at the call site.
    "JaxEngine._process_window",
    "JaxEngine._process_prefill",
    "JaxEngine._dispatch_prefill",
    "JaxEngine._land_inflight_offloads",
})

# DL006: modules allowed to touch os.environ directly (the registry itself).
ENV_ALLOWED_SUFFIXES = ("runtime/config.py",)

# DL018: profiler code paths — any module whose basename names profiling
# (runtime/profiling.py, engine/profiler.py, fixtures). In these files a
# host-sync primitive is legitimate ONLY as the deliberate sampled
# measurement, which must be lexically under an `if` whose condition
# references a sampling/enabled flag — so sample=0 provably costs no
# sync. The guard-name pattern accepts the obvious spellings.
PROFILER_PATH_RE = re.compile(r"(^|/)[A-Za-z0-9_]*profil[A-Za-z0-9_]*\.py$")
SAMPLE_GUARD_RE = re.compile(r"(?i)(sampl|enabled|active|armed)")

# DL007: the span-starting call (method or bare name) and the attribute
# accesses that count as "the span is closed somewhere".
SPAN_START_ATTRS = frozenset({"start_span"})
SPAN_CLOSE_ATTRS = frozenset({"end", "__exit__"})

# DL011: awaited calls that park on a network peer. A naked await on one
# of these wedges its task forever if the peer dies silently; they must
# run under asyncio.wait_for / guard.bound (the await's TOP-LEVEL call),
# or carry an inline disable with a justification (idle server reads
# whose lifetime IS the connection). Method names:
NET_AWAIT_ATTRS = frozenset({"drain", "readexactly", "readline",
                             "readuntil", "wait_closed"})
# dotted/bare call names (codec.decode and read_frame are this tree's
# frame-read primitives — readexactly under the hood):
NET_AWAIT_CALLS = frozenset({"asyncio.open_connection", "open_connection",
                             "codec.decode", "decode", "read_frame"})
# `await <recv>.get()` counts when the receiver is queue-shaped (its
# final segment names a queue); `seq.out.get()` et al. stay exempt.
NET_QUEUE_RE = re.compile(r"(?i)(^|[._])(queue|q)$")

SUPPRESS_RE = re.compile(r"#\s*dynalint:\s*disable=([A-Za-z0-9_,\-]+)")


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    col: int
    code: str
    name: str
    message: str
    scope: str  # dotted qualname of the enclosing class/function context

    @property
    def baseline_key(self) -> str:
        return f"{self.path}::{self.name}::{self.scope}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.code} "
                f"[{self.name}] {self.message} (in {self.scope})")

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "name": self.name,
                "message": self.message, "scope": self.scope}


# --------------------------------------------------------------- AST helpers

def dotted(node: ast.AST) -> Optional[str]:
    """'time.sleep' for Name/Attribute chains; None when the base is an
    arbitrary expression (then only the final attribute is matchable)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_attr(call: ast.Call) -> Optional[str]:
    """Final attribute name of a method-style call, e.g. 'item' for
    ``x[0].item()``."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _is_blocking_call(call: ast.Call) -> bool:
    d = dotted(call.func)
    if d is None:
        return False
    if d in BLOCKING_CALLS or d in BLOCKING_BUILTINS:
        return True
    return any(d.startswith(p) for p in BLOCKING_PREFIXES)


def _task_ref_key(node: ast.AST, class_scope: str,
                  func_id: int) -> Optional[Tuple]:
    """Key identifying a task-holding variable: self-attributes key on the
    enclosing class (stop() cancels what start() spawned); bare names key
    on the enclosing function."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return ("attr", class_scope, node.attr)
    if isinstance(node, ast.Name):
        return ("local", func_id, node.id)
    return None


class _Analyzer(ast.NodeVisitor):
    def __init__(self, path: str, suppressed: Dict[int, Set[str]]):
        self.path = path
        self.suppressed = suppressed
        self.violations: List[Violation] = []
        # context stacks
        self._classes: List[str] = []
        self._funcs: List[Tuple[str, bool]] = []  # (name, is_async)
        self._func_ids: List[int] = []
        self._loop_depth: List[int] = [0]   # per-function frame
        self._lock_depth: List[int] = [0]   # per-function frame
        # DL002 two-phase state
        self._spawn_candidates: List[Tuple[Tuple, Violation]] = []
        self._tracked_keys: Set[Tuple] = set()
        # DL007 two-phase state (same shape: candidates resolved at EOF)
        self._span_candidates: List[Tuple[Tuple, Violation]] = []
        self._span_closed_keys: Set[Tuple] = set()
        norm = path.replace(os.sep, "/")
        self._is_engine = any(m in norm for m in HOT_PATH_MARKERS)
        self._env_allowed = norm.endswith(ENV_ALLOWED_SUFFIXES)
        # DL018 state: per-function sample-guard nesting depth
        self._is_profiler = bool(PROFILER_PATH_RE.search(norm))
        self._guard_depth: List[int] = [0]

    # ------------------------------------------------------------- reporting

    def _scope(self) -> str:
        parts = self._classes + [n for n, _ in self._funcs]
        return ".".join(parts) if parts else "<module>"

    def report(self, node: ast.AST, code: str,
               detail: str = "") -> Optional[Violation]:
        name, summary = RULES[code]
        line = getattr(node, "lineno", 0)
        for probe in (line, line - 1):
            tags = self.suppressed.get(probe)
            if tags and (name in tags or code in tags or "all" in tags):
                return None
        msg = f"{summary}: {detail}" if detail else summary
        v = Violation(self.path, line, getattr(node, "col_offset", 0),
                      code, name, msg, self._scope())
        return v

    def emit(self, node: ast.AST, code: str, detail: str = "") -> None:
        v = self.report(node, code, detail)
        if v is not None:
            self.violations.append(v)

    # --------------------------------------------------------------- scoping

    def _enter_func(self, node, is_async: bool) -> None:
        name = getattr(node, "name", "<lambda>")
        self._funcs.append((name, is_async))
        self._func_ids.append(id(node))
        self._loop_depth.append(0)
        self._lock_depth.append(0)
        self._guard_depth.append(0)

    def _exit_func(self) -> None:
        self._funcs.pop()
        self._func_ids.pop()
        self._loop_depth.pop()
        self._lock_depth.pop()
        self._guard_depth.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_func(node, False)
        self.generic_visit(node)
        self._exit_func()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_func(node, True)
        self.generic_visit(node)
        self._exit_func()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_func(node, False)
        self.generic_visit(node)
        self._exit_func()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    @property
    def _in_async(self) -> bool:
        return bool(self._funcs) and self._funcs[-1][1]

    @property
    def _class_scope(self) -> str:
        return ".".join(self._classes) if self._classes else "<module>"

    @property
    def _func_id(self) -> int:
        return self._func_ids[-1] if self._func_ids else 0

    # ----------------------------------------------------------------- loops

    def _visit_loop(self, node) -> None:
        self._loop_depth[-1] += 1
        self.generic_visit(node)
        self._loop_depth[-1] -= 1

    visit_While = _visit_loop
    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    # ------------------------------------------------------ DL018 guard scope

    def visit_If(self, node: ast.If) -> None:
        """Track sample-guard nesting in profiler modules: only the
        guarded BODY is sanctioned for deliberate syncs — the orelse is
        the not-sampling branch and stays unguarded."""
        guarded = self._is_profiler and _is_sample_guard(node.test)
        self.visit(node.test)
        if guarded:
            self._guard_depth[-1] += 1
        for stmt in node.body:
            self.visit(stmt)
        if guarded:
            self._guard_depth[-1] -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    # ------------------------------------------------------ DL003 broad except

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._loop_depth[-1] > 0 and _is_broad_except(node.type) \
                and not _handler_surfaces_error(node):
            self.emit(node, "DL003")
        self.generic_visit(node)

    # ----------------------------------------------------------- DL004 locks

    def _visit_with(self, node) -> None:
        locky = any(_is_lock_expr(item.context_expr) for item in node.items)
        if locky:
            self._lock_depth[-1] += 1
        for item in node.items:
            # DL007: `with span:` closes a previously-started span variable
            self._note_span_closed(item.context_expr)
        self.generic_visit(node)
        if locky:
            self._lock_depth[-1] -= 1

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # ----------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        attr = call_attr(node)

        if _is_blocking_call(node):
            what = d or attr or "call"
            if self._in_async:
                self.emit(node, "DL001", f"`{what}`")
            if self._lock_depth[-1] > 0:
                self.emit(node, "DL004", f"blocking `{what}` under lock")

        if d in SPAWN_CALLS:
            self._record_spawn(node, d)
        if d in TRACKING_SINKS or attr in ("gather", "wait", "wait_for"):
            for arg in node.args:
                self._note_tracked(arg)

        if attr in SPAN_START_ATTRS or d in SPAN_START_ATTRS:
            self._record_span_start(node)
        else:
            # escape analysis: a span VARIABLE handed to any call transfers
            # ownership (e.g. a relay helper that ends it) — only plain
            # name/attribute args count, so literals don't mask candidates
            for arg in node.args:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    self._note_span_closed(arg)

        if self._is_engine and self._in_hot_func():
            self._check_host_sync(node, d, attr)

        if self._is_profiler and self._guard_depth[-1] == 0:
            what = host_sync_what(node, d, attr)
            if what is not None:
                self.emit(node, "DL018", f"{what} outside a sample guard")

        if not self._env_allowed:
            self._check_env_read(node, d)

        self.generic_visit(node)

    # ----------------------------------------------------------- DL002 spawn

    def _record_spawn(self, node: ast.Call, d: str) -> None:
        parent = getattr(node, "_dl_parent", None)
        # tracked forms: the task object escapes to something that owns it
        if isinstance(parent, (ast.Return, ast.Await)):
            return
        if isinstance(parent, ast.Call):
            # passed straight into gather()/wait()/... or any wrapper
            return
        if isinstance(parent, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                               ast.DictComp)):
            # `[create_task(...) for ...]`: the list escapes to whatever
            # consumes the comprehension — assume it is awaited/cancelled
            return
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
            targets = (parent.targets if isinstance(parent, ast.Assign)
                       else [parent.target])
            for t in targets:
                key = _task_ref_key(t, self._class_scope, self._func_id)
                if key is None:
                    return  # e.g. tuple unpack / subscript: assume tracked
                v = self.report(node, "DL002",
                                f"`{d}` result assigned to "
                                f"`{ast.unparse(t)}` but never cancelled, "
                                f"awaited, or given a done-callback")
                if v is not None:
                    self._spawn_candidates.append((key, v))
            return
        # bare expression statement (or anything else): result dropped
        self.emit(node, "DL002", f"`{d}` result is dropped")

    def _note_tracked(self, node: ast.AST) -> None:
        if isinstance(node, ast.Starred):
            node = node.value
        key = _task_ref_key(node, self._class_scope, self._func_id)
        if key is not None:
            self._tracked_keys.add(key)

    # ------------------------------------------------------ DL007 open spans

    def _record_span_start(self, node: ast.Call) -> None:
        parent = getattr(node, "_dl_parent", None)
        # closed forms: `with tracer.start_span(...)`, returned, awaited,
        # or passed straight into a call that takes ownership
        if isinstance(parent, (ast.withitem, ast.Return, ast.Await,
                               ast.Call)):
            return
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
            targets = (parent.targets if isinstance(parent, ast.Assign)
                       else [parent.target])
            for t in targets:
                key = _task_ref_key(t, self._class_scope, self._func_id)
                if key is None:
                    return  # exotic target: assume tracked
                v = self.report(node, "DL007",
                                f"span assigned to `{ast.unparse(t)}` but "
                                f"never entered (`with`) or end()ed")
                if v is not None:
                    self._span_candidates.append((key, v))
            return
        # bare expression statement: the Span object is dropped unclosed
        self.emit(node, "DL007", "span result is dropped")

    def _note_span_closed(self, node: ast.AST) -> None:
        key = _task_ref_key(node, self._class_scope, self._func_id)
        if key is not None:
            self._span_closed_keys.add(key)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            # a returned span escapes to the caller (who owns closing it)
            self._note_span_closed(node.value)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in TRACKING_ATTRS:
            self._note_tracked(node.value)
        if node.attr in SPAN_CLOSE_ATTRS:
            self._note_span_closed(node.value)
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await) -> None:
        self._note_tracked(node.value)
        # DL004: long awaits under a held lock
        if self._lock_depth[-1] > 0 and isinstance(node.value, ast.Call):
            d = dotted(node.value.func)
            attr = call_attr(node.value)
            if d in LONG_AWAIT_CALLS or attr in LONG_AWAIT_ATTRS:
                what = d or f".{attr}()"
                self.emit(node, "DL004", f"long `await {what}` under lock")
        self._check_unbounded_await(node)
        self.generic_visit(node)

    # ------------------------------------------------- DL011 unbounded await

    def _check_unbounded_await(self, node: ast.Await) -> None:
        """Flag ``await <net primitive>(...)`` at the await's top level.
        A wrapped form — ``await asyncio.wait_for(prim(...), t)`` or
        ``await guard.bound(prim(...), ...)`` — never fires, because the
        awaited call is then the wrapper, not the primitive."""
        if not isinstance(node.value, ast.Call):
            return
        call = node.value
        d = dotted(call.func)
        attr = call_attr(call)
        if d in NET_AWAIT_CALLS:
            self.emit(node, "DL011", f"`await {d}(...)`")
            return
        if attr in NET_AWAIT_ATTRS:
            self.emit(node, "DL011", f"`await ....{attr}()`")
            return
        if attr == "get" and isinstance(call.func, ast.Attribute):
            recv = dotted(call.func.value)
            if recv is not None and NET_QUEUE_RE.search(recv):
                self.emit(node, "DL011", f"`await {recv}.get()`")

    # -------------------------------------------------------- DL005 host sync

    def _in_hot_func(self) -> bool:
        for name, _ in reversed(self._funcs):
            if name == "<lambda>":
                continue
            if not hot_frame_re().search(name):
                return False
            qual = ".".join(self._classes + [name])
            return qual not in HOT_SYNC_ALLOWLIST
        return False

    def _check_host_sync(self, node: ast.Call, d: Optional[str],
                         attr: Optional[str]) -> None:
        what = host_sync_what(node, d, attr)
        if what is not None:
            self.emit(node, "DL005", what)

    # --------------------------------------------------------- DL006 env read

    def _check_env_read(self, node: ast.Call, d: Optional[str]) -> None:
        if d in ("os.getenv", "os.environ.get", "os.environ.setdefault"):
            arg = node.args[0] if node.args else None
            name = (repr(arg.value) if isinstance(arg, ast.Constant)
                    else "<dynamic>")
            self.emit(node, "DL006", f"`{d}({name})`")

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if not self._env_allowed and isinstance(node.ctx, ast.Load) \
                and dotted(node.value) == "os.environ":
            self.emit(node, "DL006", "`os.environ[...]`")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        if not self._env_allowed and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops) \
                and any(dotted(c) == "os.environ" for c in node.comparators):
            self.emit(node, "DL006", "`... in os.environ`")
        self.generic_visit(node)

    # -------------------------------------------------------------- finalize

    def finalize(self) -> List[Violation]:
        for key, violation in self._spawn_candidates:
            if key not in self._tracked_keys:
                self.violations.append(violation)
        for key, violation in self._span_candidates:
            if key not in self._span_closed_keys:
                self.violations.append(violation)
        self.violations.sort(key=lambda v: (v.path, v.line, v.code))
        return self.violations


def _is_broad_except(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:  # bare except:
        return True
    names = ([type_node] if not isinstance(type_node, ast.Tuple)
             else list(type_node.elts))
    return any(isinstance(n, ast.Name) and
               n.id in ("Exception", "BaseException") for n in names)


def _handler_surfaces_error(handler: ast.ExceptHandler) -> bool:
    """A handler is fine when it logs, backs off, or exits the loop."""
    for sub in ast.walk(handler):
        if isinstance(sub, (ast.Raise, ast.Break, ast.Return)):
            return True
        if isinstance(sub, ast.Call):
            d = dotted(sub.func)
            attr = call_attr(sub)
            if attr in LOG_METHODS or \
                    (isinstance(sub.func, ast.Name)
                     and sub.func.id == "print"):
                return True
            if d in ("time.sleep", "asyncio.sleep"):
                return True
    return False


def host_sync_what(call: ast.Call, d: Optional[str],
                   attr: Optional[str]) -> Optional[str]:
    """Host-sync primitive detection shared by the per-file DL005 rule
    and the interprocedural (callgraph) DL005 pass. Returns a display
    string for the primitive, or None."""
    if d in HOST_SYNC_CALLS or attr == "block_until_ready":
        return f"`{d or attr}`"
    if attr == "item" and not call.args:
        return "`.item()`"
    if isinstance(call.func, ast.Name) and call.func.id == "float" \
            and call.args and isinstance(
                call.args[0], (ast.Call, ast.Subscript)):
        return "`float()` on a computed value"
    return None


def _is_sample_guard(test: ast.AST) -> bool:
    """True when an `if` condition references a sampling/enabled flag
    (any Name or attribute segment matching SAMPLE_GUARD_RE)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Name) and SAMPLE_GUARD_RE.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and \
                SAMPLE_GUARD_RE.search(sub.attr):
            return True
    return False


def _is_lock_expr(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):  # e.g. `with threading.Lock():`
        expr = expr.func
    d = dotted(expr)
    if d is None:
        return False
    return bool(LOCK_NAME_RE.search(d.rsplit(".", 1)[-1]))


# ------------------------------------------------------------------ frontend

@dataclass
class ModuleSource:
    """One parsed module, shared by every rule pass in a run. The parse
    cache below exists because the per-file pass, the dynaflow
    call-graph pass and the wire-conformance pass all want the same
    trees — before it, each whole-program rule re-read and re-parsed
    every file (the analyzer did the whole tree once per pass)."""

    path: str                       # root-relative display path ('/'-sep)
    abspath: str
    src: str
    tree: ast.AST
    suppressed: Dict[int, Set[str]]


# abspath -> (content_sha1, ModuleSource). Keyed on content hash, NOT
# (mtime_ns, size): a same-size rewrite within one mtime granule (editor
# save + re-save, test fixtures on coarse-mtime filesystems) left the
# old stat key unchanged and served a stale tree. The file is already
# being read into memory for the parse, so hashing it is ~free.
_SOURCE_CACHE: Dict[str, Tuple[str, ModuleSource]] = {}


def parse_module(src: str, path: str) -> ModuleSource:
    """In-memory ModuleSource (fixtures, tests) — bypasses the disk cache."""
    rel = path.replace(os.sep, "/")
    tree = ast.parse(src, filename=rel)
    _annotate_parents(tree)
    return ModuleSource(rel, rel, src, tree, _collect_suppressions(src))


def load_source(abspath: str, rel: str) -> ModuleSource:
    """Parse (or fetch from the per-process cache) one module."""
    with open(abspath, "rb") as fh:
        data = fh.read()
    key = hashlib.sha1(data).hexdigest()
    hit = _SOURCE_CACHE.get(abspath)
    if hit is not None and hit[0] == key:
        return hit[1]
    src = data.decode("utf-8")
    rel = rel.replace(os.sep, "/")
    tree = ast.parse(src, filename=rel)
    _annotate_parents(tree)
    ms = ModuleSource(rel, abspath, src, tree, _collect_suppressions(src))
    _SOURCE_CACHE[abspath] = (key, ms)
    return ms


def load_sources(paths: Sequence[str],
                 root: Optional[str] = None) -> List[ModuleSource]:
    """Load every .py under ``paths`` through the parse cache; display
    paths are root-relative. Unparseable files are skipped here — the
    per-file pass reports them as DL000."""
    root = os.path.abspath(root or os.getcwd())
    out: List[ModuleSource] = []
    for f in iter_py_files(paths):
        ab = os.path.abspath(f)
        rel = os.path.relpath(ab, root) if ab.startswith(root + os.sep) else f
        try:
            out.append(load_source(ab, rel))
        except SyntaxError:
            continue
    return out


def _collect_suppressions(src: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if m:
            out[i] = {t.strip() for t in m.group(1).split(",") if t.strip()}
    return out


def _annotate_parents(tree: ast.AST) -> None:
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._dl_parent = parent  # type: ignore[attr-defined]


def analyze_source(src: str, path: str) -> List[Violation]:
    """Analyze one module's source. ``path`` drives the path-scoped rules
    (DL005 engine modules, DL006 config allowlist) and appears in output."""
    tree = ast.parse(src, filename=path)
    _annotate_parents(tree)
    analyzer = _Analyzer(path.replace(os.sep, "/"),
                         _collect_suppressions(src))
    analyzer.visit(tree)
    return analyzer.finalize()


def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    return sorted(set(files))


def analyze_module(ms: ModuleSource) -> List[Violation]:
    """Per-file rule pass over an already-parsed module (cache-friendly
    twin of :func:`analyze_source`)."""
    analyzer = _Analyzer(ms.path, ms.suppressed)
    analyzer.visit(ms.tree)
    return analyzer.finalize()


def analyze_paths(paths: Sequence[str],
                  root: Optional[str] = None) -> List[Violation]:
    """Run the per-file rules on every .py under ``paths``; reported paths
    are relative to ``root`` (default: cwd) so baseline entries are
    location-independent. Parses go through the shared source cache."""
    root = os.path.abspath(root or os.getcwd())
    out: List[Violation] = []
    for f in iter_py_files(paths):
        ab = os.path.abspath(f)
        rel = os.path.relpath(ab, root) if ab.startswith(root + os.sep) else f
        try:
            out.extend(analyze_module(load_source(ab, rel)))
        except SyntaxError as e:
            out.append(Violation(rel.replace(os.sep, "/"), e.lineno or 0, 0,
                                 "DL000", "syntax-error", str(e), "<module>"))
    return out
