"""modelcheck: exhaustive async-interleaving exploration of the declared
lifecycle protocols (the TLA-style half of dynaproto).

Each machine declared in ``dynamo_tpu/runtime/proto.py`` is a finite
transition system: the ``state`` variable plus its declared auxiliary
vars, protocol edges (guarded, with updates — every one anchored to a
real code site by DL020) and environment transitions (client kills,
worker deaths, message loss — the nondeterminism the protocol must
survive). This module explores EVERY reachable interleaving by
deterministic breadth-first search, bounded by the machine's declared
``depth``, and checks the declared invariants:

- ``never`` — the predicate holds in **no** reachable state;
- ``never_stable`` — the predicate holds in no *quiescent* state (one
  with no enabled protocol edge): the bounded form of "eventually" —
  e.g. a finished request whose journal entry is still open is fine
  only while a close edge is still enabled.

A violated invariant is reported as a DL020 violation at the machine's
registration line, with a counterexample trace (the transition names
from the initial state to the offending one). The per-machine
exploration report — state count, transition count, whether the search
exhausted the space inside the depth bound — feeds ``--json``'s
``protocols`` block and the model↔code sync-gate test.

Everything is stdlib and deterministic: vars are sorted, transitions
fire in declaration order, states are canonical tuples — two runs over
one registry are byte-identical.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .analyzer import RULES, Violation
from .dynaproto import PROTO_MODULE_REL, ProtoSchema

State = Tuple[object, ...]   # values ordered by the machine's var order


@dataclass
class ModelResult:
    machine: str
    var_names: Tuple[str, ...]
    states_explored: int = 0
    transitions_fired: int = 0
    exhausted: bool = True        # False when the depth bound cut BFS off
    quiescent_states: int = 0
    violations: List[dict] = field(default_factory=list)
    # {invariant, state: {var: val}, trace: [transition names]}


def _var_order(schema: ProtoSchema) -> Tuple[str, ...]:
    return ("state",) + tuple(k for k, _dom in schema.vars)


def _domains(schema: ProtoSchema) -> Dict[str, tuple]:
    doms = {"state": tuple(schema.states)}
    doms.update({k: tuple(v) for k, v in schema.vars})
    return doms


def _initial(schema: ProtoSchema, order: Tuple[str, ...]) -> State:
    init = {"state": schema.initial}
    init.update(dict(schema.init))
    return tuple(init.get(v) for v in order)


def _enabled(tr: dict, state: State, idx: Dict[str, int]) -> bool:
    frm = tr.get("from")
    if frm:
        if state[idx["state"]] != frm:
            return False
    for var, allowed in tr["when"].items():
        if var not in idx or state[idx[var]] not in allowed:
            return False
    return True


def _apply(tr: dict, state: State, idx: Dict[str, int],
           doms: Dict[str, tuple]) -> Optional[State]:
    """Successor state, or None when an update leaves a var's domain
    (the explored counter invariants catch that as `never` on the max
    value instead — see the `+1` convention)."""
    out = list(state)
    if tr.get("to"):
        out[idx["state"]] = tr["to"]
    for var, val in sorted(tr["set"].items()):
        if var not in idx:
            continue
        if val == "+1":
            cur = out[idx[var]]
            val = (cur + 1) if isinstance(cur, int) else cur
        elif val == "-1":
            cur = out[idx[var]]
            val = (cur - 1) if isinstance(cur, int) else cur
        if val not in doms[var]:
            return None   # clamped off the domain edge: not a new state
        out[idx[var]] = val
    return tuple(out)


def _pred_holds(pred: dict, state: State, idx: Dict[str, int]) -> bool:
    for var, want in pred.items():
        if var not in idx:
            return False
        allowed = tuple(want) if isinstance(want, (tuple, list)) else (want,)
        if state[idx[var]] not in allowed:
            return False
    return True


def explore(schema: ProtoSchema) -> ModelResult:
    """Deterministic BFS over one machine composed with its declared
    environment."""
    order = _var_order(schema)
    idx = {v: i for i, v in enumerate(order)}
    doms = _domains(schema)
    init = _initial(schema, order)
    result = ModelResult(machine=schema.name, var_names=order)

    protocol = list(schema.edges)
    transitions = protocol + list(schema.env)

    # predecessor map for counterexample traces
    parent: Dict[State, Tuple[Optional[State], str]] = {init: (None, "")}
    frontier = deque([init])
    depth = 0
    seen = {init}
    while frontier and depth < schema.depth:
        depth += 1
        for _ in range(len(frontier)):
            st = frontier.popleft()
            for tr in transitions:
                if not _enabled(tr, st, idx):
                    continue
                nxt = _apply(tr, st, idx, doms)
                if nxt is None:
                    continue
                result.transitions_fired += 1
                if nxt not in seen:
                    seen.add(nxt)
                    parent[nxt] = (st, tr["name"])
                    frontier.append(nxt)
    if frontier:
        result.exhausted = False
    result.states_explored = len(seen)

    def trace(state: State) -> List[str]:
        names: List[str] = []
        cur: Optional[State] = state
        while cur is not None:
            prev, name = parent[cur]
            if name:
                names.append(name)
            cur = prev
        return list(reversed(names))

    def fmt(state: State) -> Dict[str, object]:
        return {v: state[idx[v]] for v in order}

    ordered = sorted(seen)
    quiescent = []
    for st in ordered:
        if not any(_enabled(tr, st, idx) for tr in protocol):
            quiescent.append(st)
    result.quiescent_states = len(quiescent)

    edges_by_name = {e["name"]: e for e in protocol}
    for inv in schema.invariants:
        name = inv.get("name", "?")
        if "never_fire" in inv:
            # transition-level: no listed edge may be ENABLED in any
            # reachable state satisfying the predicate (guard checking:
            # "no resume is ever dispatched after a client kill")
            spec = inv["never_fire"]
            targets = spec.get("edges") or ()
            if isinstance(targets, str):
                targets = (targets,)
            pred = spec.get("when") or {}
            hit = None
            for st in ordered:
                for ename in targets:
                    e = edges_by_name.get(ename)
                    if e is None:
                        continue
                    if _enabled(e, st, idx) and _pred_holds(pred, st, idx):
                        hit = (st, ename)
                        break
                if hit:
                    break
            if hit:
                result.violations.append({
                    "invariant": name, "state": fmt(hit[0]),
                    "stable": False, "edge": hit[1],
                    "trace": trace(hit[0])})
            continue
        if "never" in inv:
            pred, pool = inv["never"], ordered
        elif "never_stable" in inv:
            pred, pool = inv["never_stable"], quiescent
        else:
            continue
        for st in pool:
            if _pred_holds(pred, st, idx):
                result.violations.append({
                    "invariant": name, "state": fmt(st),
                    "stable": "never_stable" in inv,
                    "trace": trace(st)})
                break   # one counterexample per invariant is enough
    return result


def check_models(schemas: Dict[str, ProtoSchema],
                 proto_path: str = PROTO_MODULE_REL,
                 suppressed: Optional[Dict[int, set]] = None,
                 report_out: Optional[dict] = None) -> List[Violation]:
    """Explore every registered machine; invariant violations become
    DL020 findings at the machine's registration line. ``report_out``
    receives the per-machine exploration stats for ``--json``."""
    out: List[Violation] = []
    name, summary = RULES["DL020"]
    report: Dict[str, dict] = {}
    for mname in sorted(schemas):
        schema = schemas[mname]
        res = explore(schema)
        report[mname] = {
            "states": len(schema.states),
            "edges": len(schema.edges),
            "env_transitions": len(schema.env),
            "invariants": len(schema.invariants),
            "model_states": res.states_explored,
            "model_transitions": res.transitions_fired,
            "quiescent_states": res.quiescent_states,
            "exhausted": res.exhausted,
        }
        if not res.exhausted:
            sup = (suppressed or {}).get(schema.line) or \
                (suppressed or {}).get(schema.line - 1)
            if not (sup and ({"DL020", name, "all"} & sup)):
                out.append(Violation(
                    proto_path, schema.line, 0, "DL020", name,
                    f"{summary}: machine `{mname}` state space not "
                    f"exhausted within depth {schema.depth} "
                    f"({res.states_explored} states reached) — raise "
                    f"`depth` or shrink a var domain", mname))
        for v in res.violations:
            sup = (suppressed or {}).get(schema.line) or \
                (suppressed or {}).get(schema.line - 1)
            if sup and ({"DL020", name, "all"} & sup):
                continue
            if v.get("edge"):
                kind = f"reachable with edge `{v['edge']}` enabled"
            elif v["stable"]:
                kind = "holds in a quiescent state"
            else:
                kind = "reachable"
            out.append(Violation(
                proto_path, schema.line, 0, "DL020", name,
                f"{summary}: machine `{mname}` invariant "
                f"`{v['invariant']}` violated — forbidden state "
                f"{v['state']} is {kind} via "
                f"[{' -> '.join(v['trace']) or '<initial>'}]", mname))
    if report_out is not None:
        report_out.update(report)
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out


def check_protocol_models(sources: Sequence,
                          schemas: Optional[Dict[str, ProtoSchema]] = None,
                          proto_path: str = PROTO_MODULE_REL,
                          report_out: Optional[dict] = None
                          ) -> List[Violation]:
    """Driver twin of dynaproto.analyze_protocols: load the registry
    from the scanned tree (or use ``schemas``) and model-check it."""
    from .dynaproto import load_protocols

    suppressed = None
    if schemas is None:
        proto_ms = next((m for m in sources if m.path == proto_path), None)
        if proto_ms is None:
            return []
        schemas, _bad = load_protocols(proto_ms)
        suppressed = proto_ms.suppressed
    return check_models(schemas, proto_path=proto_path,
                        suppressed=suppressed, report_out=report_out)
