"""dynarace: whole-program async race & atomicity analysis (DL012-DL014).

Dynamo's Rust runtime is data-race-free by construction — ``Send``/``Sync``
bounds and ``Mutex<T>`` make unguarded sharing unrepresentable. This
Python port's cooperative concurrency has a subtler failure mode: nothing
ever runs in parallel, but **every ``await`` is a preemption point**, so
any read-check-act sequence over shared state that straddles an await can
interleave with another task and act on a stale view. No per-file rule
can see it, because "shared" is a whole-program property.

Built on :mod:`callgraph`, this pass:

1. infers **concurrency roots** — every ``spawn_tracked`` /
   ``create_task`` / ``ensure_future`` site (spawned-in-a-loop roots are
   reentrant), every handler reference registered via ``subscribe(...)``
   or an aiohttp route table (reentrant: they fire per message/request),
   and every async def nothing in the project calls (an API entry point
   servers/tests invoke — reentrant, conservatively);
2. computes which functions each root reaches, and upgrades spawns made
   from already-concurrent code to reentrant (fixpoint);
3. models **shared state** as ``self.<attr>`` object attributes whose
   accesses span ≥2 roots (or any reentrant root); and
4. checks three interprocedural rules plus the ``# guarded-by:``
   annotation discipline:

- **DL012 atomicity-across-await** — a shared attribute loaded at one
  await-epoch and plain-stored at a later epoch in the same (async)
  function, with no re-read after the last await and no lock common to
  both accesses. This is the lost-update / stale-check shape:
  ``v = self.x`` … ``await`` … ``self.x = f(v)``, or
  ``if not self.x:`` … ``await`` … ``self.x = y``. Single-statement
  mutations (``+=``, ``d[k] = v``, ``.append``) are atomic under the
  event loop and never fire on their own; the sanctioned fix is to
  re-check after the await (double-checked update) or hold one lock
  across the whole sequence.
- **DL013 unguarded-concurrent-mutation** — (a) an access to a field
  annotated ``# guarded-by: self.<lock>`` from an async frame in a
  concurrent context without that lock held; (b) a ``guarded-by``
  annotation naming a lock the class never assigns; (c) an unannotated
  shared field mutated under some lock at one site and without it at
  another async-frame site (inconsistent discipline, RacerD-style).
- **DL014 lock-order-inversion** — two locks acquired in opposite
  nesting orders anywhere in the program (lexical nesting plus calls
  made while holding a lock into functions that acquire others): two
  tasks taking them concurrently deadlock the loop forever.

**The guarded-by grammar** (attach to the attribute's assignment line,
or the line above):

- ``# guarded-by: self.<lock_attr>`` — lock discipline: every access
  from an *async* frame of the class must be lexically under
  ``with``/``async with self.<lock_attr>``. Sync frames are exempt — a
  sync call cannot be preempted by the event loop, so it is atomic; the
  lock is required exactly where control can yield.
- ``# guarded-by: loop`` — event-loop affinity: the field relies on
  single-threaded atomicity, so DL012 is enforced on it
  *unconditionally* (any async frame, shared or not). This is the
  right annotation for demux tables and bookkeeping dicts that only
  ever see single-statement accesses.

Like every dynalint rule, ``# dynalint: disable=<rule>`` suppresses at
the reported line; DL012 additionally honors a suppression at the
pre-await read line (both ends, like DL008's call-site/sink pair).

The same callgraph drives the interprocedural extension of **DL005**:
a host-sync primitive (``np.asarray``, ``.item()``, ``block_until_ready``)
reached from an engine hot-path function through a chain of sync helpers
fires at the hot function's call site, with ``HOT_SYNC_ALLOWLIST``
members excluded both as origins and as sanctioned sinks.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .analyzer import (HOT_SYNC_ALLOWLIST, LOCK_NAME_RE, RULES,
                       ModuleSource, Violation, call_attr, dotted)
from .callgraph import DEFAULT_DL008_DEPTH, CallGraph, module_name
from .dynahot import HOT_FRAME_RE

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*|loop)")

# receiver methods that mutate the container in place: `self.A.pop(...)`
# is a MUTATION of A for the discipline rules (but a single synchronous
# statement, so atomic — it never fires DL012 by itself)
MUTATOR_ATTRS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse", "move_to_end", "put_nowait",
})

_HOT_PATH_MARKER = "engine/"


# ------------------------------------------------------------------ scanning

@dataclass
class Access:
    attr: str
    kind: str                 # 'load' | 'store' | 'mut'
    line: int
    col: int
    epoch: int                # awaits/yields seen before this access
    locks: FrozenSet[str]     # normalized lock ids held


@dataclass
class FuncScan:
    key: str                  # '<module>:<qualname>' (matches callgraph)
    cls: Optional[str]        # owner class name, None for free functions
    is_async: bool
    accesses: List[Access] = field(default_factory=list)
    # locks this function acquires anywhere in its body
    acquires: Set[str] = field(default_factory=set)
    # (held_lock, acquired_lock, line) lexical nesting orders
    orders: List[Tuple[str, str, int]] = field(default_factory=list)
    # (callee_raw, held_locks, line) calls made while holding ≥1 lock
    calls_under_lock: List[Tuple[str, FrozenSet[str], int]] = \
        field(default_factory=list)


@dataclass
class ModuleScan:
    ms: ModuleSource
    modname: str
    funcs: Dict[str, FuncScan] = field(default_factory=dict)
    # (class, attr) -> (spec, line); spec is 'loop' or 'self.<lock_attr>'
    guarded: Dict[Tuple[str, str], Tuple[str, int]] = \
        field(default_factory=dict)
    # class -> attrs ever assigned through self (lock existence check)
    class_attrs: Dict[str, Set[str]] = field(default_factory=dict)


class _RaceScan(ast.NodeVisitor):
    """One pass per module: attribute accesses with await-epoch and
    held-lock context, lock acquisition orders, guarded-by annotations."""

    def __init__(self, ms: ModuleSource):
        self.out = ModuleScan(ms, module_name(ms.path))
        # line -> (spec, standalone): a trailing comment binds only to
        # its own line; a standalone comment line binds to the next
        self._annot: Dict[int, Tuple[str, bool]] = {}
        for i, line in enumerate(ms.src.splitlines(), start=1):
            m = GUARDED_BY_RE.search(line)
            if m:
                standalone = not line.split("#", 1)[0].strip()
                self._annot[i] = (m.group(1), standalone)
        self._classes: List[str] = []
        self._frames: List[FuncScan] = []
        self._epochs: List[int] = []
        self._locks: List[List[str]] = []

    # ------------------------------------------------------------- scoping

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    def _visit_func(self, node, is_async: bool) -> None:
        # qualname: classes + enclosing function names + this name, the
        # same construction as callgraph._Collector so keys line up
        names = self._classes + self._func_names() + [node.name]
        fs = FuncScan(key=f"{self.out.modname}:{'.'.join(names)}",
                      cls=self._classes[0] if self._classes else None,
                      is_async=is_async)
        self.out.funcs[fs.key] = fs
        self._frames.append(fs)
        self._epochs.append(0)
        self._locks.append([])
        self.generic_visit(node)
        self._locks.pop()
        self._epochs.pop()
        self._frames.pop()

    def _func_names(self) -> List[str]:
        return [f.key.split(":", 1)[1].split(".")[-1] for f in self._frames]

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node, False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node, True)

    # -------------------------------------------------------------- epochs

    def visit_Await(self, node: ast.Await) -> None:
        self.generic_visit(node)       # the awaited expr runs pre-suspend
        if self._epochs:
            self._epochs[-1] += 1

    def _visit_yield(self, node) -> None:
        self.generic_visit(node)
        if self._epochs:
            self._epochs[-1] += 1      # generators interleave at yields

    visit_Yield = _visit_yield
    visit_YieldFrom = _visit_yield

    # --------------------------------------------------------------- locks

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        d = dotted(expr)
        if d is None or not LOCK_NAME_RE.search(d.rsplit(".", 1)[-1]):
            return None
        if d.startswith("self.") and self._classes:
            return f"{self.out.modname}:{self._classes[0]}.{d[5:]}"
        return f"{self.out.modname}:{d}"

    def _visit_with(self, node) -> None:
        acquired: List[str] = []
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                acquired.append(lid)
        frame = self._frames[-1] if self._frames else None
        stack = self._locks[-1] if self._locks else []
        for lid in acquired:
            if frame is not None:
                frame.acquires.add(lid)
                for held in stack:
                    if held != lid:
                        frame.orders.append((held, lid, node.lineno))
            stack.append(lid)
        self.generic_visit(node)
        for _ in acquired:
            stack.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # ------------------------------------------------------------ accesses

    def _held(self) -> FrozenSet[str]:
        return frozenset(self._locks[-1]) if self._locks else frozenset()

    def visit_Call(self, node: ast.Call) -> None:
        # calls made while holding a lock: the DL014 interprocedural
        # edge (the callee may acquire other locks)
        if self._frames:
            held = self._held()
            if held:
                d = dotted(node.func)
                if d is not None:
                    self._frames[-1].calls_under_lock.append(
                        (d, held, node.lineno))
        self.generic_visit(node)

    def _record(self, attr: str, kind: str, node: ast.AST) -> None:
        if not self._frames:
            return
        self._frames[-1].accesses.append(Access(
            attr, kind, node.lineno, getattr(node, "col_offset", 0),
            self._epochs[-1], self._held()))

    def _note_guarded(self, attr: str, line: int) -> None:
        """Bind a guarded-by annotation (trailing on the assignment
        line, or a standalone comment on the line above) to
        (class, attr)."""
        if not self._classes:
            return
        hit = self._annot.get(line)
        if hit is None:
            above = self._annot.get(line - 1)
            hit = above if above is not None and above[1] else None
        if hit is not None:
            self.out.guarded[(self._classes[0], attr)] = (hit[0], line)

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return None

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr is None:
            self.generic_visit(node)
            return
        if self._classes:
            self.out.class_attrs.setdefault(self._classes[0], set())
        parent = getattr(node, "_dl_parent", None)
        if isinstance(node.ctx, ast.Store):
            # reached via tuple targets / for-targets / withitems; plain
            # `self.x = ...` goes through _visit_store_target instead.
            # Either way the Assign visitors ran the value first, so the
            # store lands at the post-await epoch.
            if self._classes:
                self.out.class_attrs[self._classes[0]].add(attr)
            self._note_guarded(attr, node.lineno)
            self._record(attr, "store", node)
            self.generic_visit(node)
            return
        if isinstance(node.ctx, ast.Del):
            self._record(attr, "mut", node)
            self.generic_visit(node)
            return
        # Load context: classify by what encloses the attribute
        if isinstance(parent, ast.Call) and parent.func is node:
            pass  # `self.meth(...)`: a call edge, not a state access
        elif isinstance(parent, ast.Attribute) and parent.value is node:
            gp = getattr(parent, "_dl_parent", None)
            if isinstance(parent.ctx, ast.Store):
                self._record(attr, "mut", node)   # self.a.b = v
            elif isinstance(gp, ast.Call) and gp.func is parent:
                self._record(attr, "mut" if parent.attr in MUTATOR_ATTRS
                             else "load", node)   # self.a.meth(...)
            else:
                self._record(attr, "load", node)
        elif isinstance(parent, ast.Subscript) and parent.value is node:
            if isinstance(parent.ctx, (ast.Store, ast.Del)):
                self._record(attr, "mut", node)   # self.a[k] = v / del
            else:
                self._record(attr, "load", node)
        else:
            self._record(attr, "load", node)
        self.generic_visit(node)

    # value-before-targets visit order so stores land at the POST-await
    # epoch for `self.x = await f()` (the suspension happens before the
    # store, which is exactly when another task can interleave)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for t in node.targets:
            self._visit_store_target(t)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
        self._visit_store_target(node.target)

    def visit_NamedExpr(self, node: ast.NamedExpr) -> None:
        self.visit(node.value)
        self._visit_store_target(node.target)

    def _visit_store_target(self, t: ast.AST) -> None:
        attr = self._self_attr(t)
        if attr is not None:
            if self._classes:
                self.out.class_attrs.setdefault(
                    self._classes[0], set()).add(attr)
            self._note_guarded(attr, t.lineno)
            self._record(attr, "store", t)
            return
        self.visit(t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._self_attr(node.target)
        if attr is not None:
            # `self.x += v`: load first, evaluate v (awaits bump the
            # epoch), then store — `self.x += await f()` IS a lost update
            self._record(attr, "load", node.target)
            self.visit(node.value)
            if self._classes:
                self.out.class_attrs.setdefault(
                    self._classes[0], set()).add(attr)
            self._record(attr, "store", node.target)
            return
        self.visit(node.target)
        self.visit(node.value)


def scan_modules(sources: Sequence[ModuleSource]) -> List[ModuleScan]:
    out = []
    for ms in sources:
        scan = _RaceScan(ms)
        scan.visit(ms.tree)
        out.append(scan.out)
    return out


# --------------------------------------------------------------- race model

@dataclass
class RootInfo:
    key: str
    kind: str            # 'task' | 'handler' | 'entry'
    reentrant: bool


@dataclass
class RaceModel:
    roots: Dict[str, RootInfo]
    func_roots: Dict[str, Set[str]]          # function key -> root keys
    shared_attrs: Set[Tuple[str, str, str]]  # (module, class, attr)
    shared_funcs: Set[str]                   # functions touching shared state

    def concurrent(self, key: str) -> bool:
        roots = self.func_roots.get(key, set())
        if len(roots) >= 2:
            return True
        return any(self.roots[r].reentrant for r in roots)


def _reach_from(graph: CallGraph, root: str) -> Set[str]:
    seen = {root}
    stack = [root]
    while stack:
        fi = graph.functions.get(stack.pop())
        if fi is None:
            continue
        for cs in fi.calls:
            if cs.target and cs.target in graph.functions \
                    and cs.target not in seen:
                seen.add(cs.target)
                stack.append(cs.target)
    return seen


def build_race_model(graph: CallGraph,
                     scans: Sequence[ModuleScan]) -> RaceModel:
    roots: Dict[str, RootInfo] = {}
    spawned: Set[str] = set()
    registered: Set[str] = set()
    for fi in graph.functions.values():
        for sp in fi.spawns:
            if sp.target and sp.target in graph.functions:
                spawned.add(sp.target)
                prev = roots.get(sp.target)
                roots[sp.target] = RootInfo(
                    sp.target, "task",
                    sp.in_loop or (prev.reentrant if prev else False))
        for hr in fi.handler_refs:
            if hr.target and hr.target in graph.functions:
                registered.add(hr.target)
                roots[hr.target] = RootInfo(hr.target, "handler", True)
    called: Set[str] = set()
    for fi in graph.functions.values():
        for cs in fi.calls:
            if cs.target:
                called.add(cs.target)
    for key, fi in graph.functions.items():
        if fi.is_async and key not in called and key not in spawned \
                and key not in registered:
            # an async def nothing in the project calls: an entry point
            # that servers/tests/users drive — conservatively reentrant
            roots.setdefault(key, RootInfo(key, "entry", True))

    func_roots: Dict[str, Set[str]] = {}
    reach_cache: Dict[str, Set[str]] = {}
    for rk in roots:
        reach_cache[rk] = _reach_from(graph, rk)
    for _round in range(8):  # reentrancy fixpoint (converges in 2-3)
        func_roots = {}
        for rk in roots:
            for f in reach_cache[rk]:
                func_roots.setdefault(f, set()).add(rk)
        changed = False
        for fi in graph.functions.values():
            frs = func_roots.get(fi.key, set())
            concurrent = len(frs) >= 2 or \
                any(roots[r].reentrant for r in frs)
            if not concurrent:
                continue
            for sp in fi.spawns:
                r = roots.get(sp.target) if sp.target else None
                if r is not None and not r.reentrant:
                    # spawned from already-concurrent code: many copies
                    # of this task can exist at once
                    roots[sp.target] = RootInfo(r.key, r.kind, True)
                    changed = True
        if not changed:
            break

    shared_attrs: Set[Tuple[str, str, str]] = set()
    shared_funcs: Set[str] = set()
    attr_roots: Dict[Tuple[str, str, str], Set[str]] = {}
    for scan in scans:
        for fs in scan.funcs.values():
            if fs.cls is None:
                continue
            for acc in fs.accesses:
                key = (scan.modname, fs.cls, acc.attr)
                attr_roots.setdefault(key, set()).update(
                    func_roots.get(fs.key, set()))
    for key, rset in attr_roots.items():
        if len(rset) >= 2 or any(roots[r].reentrant for r in rset):
            shared_attrs.add(key)
    for scan in scans:
        for fs in scan.funcs.values():
            if fs.cls is None:
                continue
            if any((scan.modname, fs.cls, a.attr) in shared_attrs
                   for a in fs.accesses):
                shared_funcs.add(fs.key)
    return RaceModel(roots, func_roots, shared_attrs, shared_funcs)


# ------------------------------------------------------------------- checks

def _suppressed(ms: ModuleSource, line: int, code: str) -> bool:
    name = RULES[code][0]
    for probe in (line, line - 1):
        tags = ms.suppressed.get(probe)
        if tags and (code in tags or name in tags or "all" in tags):
            return True
    return False


def _scope_of(key: str) -> str:
    return key.split(":", 1)[1]


def _norm_spec(scan: ModuleScan, cls: str, spec: str) -> Optional[str]:
    """'self._conn_lock' -> the normalized lock id used on accesses."""
    if spec.startswith("self."):
        return f"{scan.modname}:{cls}.{spec[5:]}"
    return None


def _check_atomicity(scan: ModuleScan, fs: FuncScan, attrs: Set[str],
                     out: List[Violation]) -> None:
    """DL012 over one function: plain store at epoch e2 with a load at an
    earlier epoch, no re-read after the last await, no common lock."""
    name, summary = RULES["DL012"]
    by_attr: Dict[str, List[Access]] = {}
    for acc in fs.accesses:
        if acc.attr in attrs:
            by_attr.setdefault(acc.attr, []).append(acc)
    for attr, accs in sorted(by_attr.items()):
        loads = [a for a in accs if a.kind == "load"]
        for st in accs:
            if st.kind != "store" or st.epoch == 0:
                continue
            if any(l.epoch == st.epoch and l.line <= st.line
                   for l in loads):
                continue  # re-validated after the last await
            stale = [l for l in loads if l.epoch < st.epoch
                     and not (l.locks & st.locks)]
            if not stale:
                continue
            first = min(stale, key=lambda l: (l.epoch, l.line))
            if _suppressed(scan.ms, st.line, "DL012") or \
                    _suppressed(scan.ms, first.line, "DL012"):
                continue
            out.append(Violation(
                scan.ms.path, st.line, st.col, "DL012", name,
                f"{summary}: `self.{attr}` read at line {first.line}, "
                f"then written here after ≥1 await with no re-check "
                f"and no common lock", _scope_of(fs.key)))


def check_races(scans: Sequence[ModuleScan],
                model: RaceModel) -> List[Violation]:
    """DL012 + DL013 over the scanned modules."""
    out: List[Violation] = []
    # global guarded-by table: (module, class, attr) -> (spec, line, scan)
    guarded: Dict[Tuple[str, str, str], Tuple[str, int, ModuleScan]] = {}
    for scan in scans:
        for (cls, attr), (spec, line) in scan.guarded.items():
            guarded[(scan.modname, cls, attr)] = (spec, line, scan)

    d13_name, d13_summary = RULES["DL013"]

    # DL013(b): the named lock must exist on the class
    for (mod, cls, attr), (spec, line, scan) in sorted(guarded.items()):
        if spec == "loop":
            continue
        lock_attr = spec[5:] if spec.startswith("self.") else None
        if lock_attr is None or \
                lock_attr not in scan.class_attrs.get(cls, set()):
            if not _suppressed(scan.ms, line, "DL013"):
                out.append(Violation(
                    scan.ms.path, line, 0, "DL013", d13_name,
                    f"{d13_summary}: `# guarded-by: {spec}` on "
                    f"`{cls}.{attr}` names a lock the class never "
                    f"assigns", cls))

    # per-mutation lock observations for the inconsistent-discipline check
    mut_locks: Dict[Tuple[str, str, str], Set[str]] = {}
    for scan in scans:
        for fs in scan.funcs.values():
            if fs.cls is None:
                continue
            for acc in fs.accesses:
                if acc.kind in ("store", "mut") and acc.locks:
                    mut_locks.setdefault(
                        (scan.modname, fs.cls, acc.attr),
                        set()).update(acc.locks)

    for scan in scans:
        for fs in sorted(scan.funcs.values(), key=lambda f: f.key):
            if fs.cls is None:
                continue
            # attrs DL012 applies to in this function: shared ones when
            # the function is concurrent, plus loop-annotated ones always
            d12_attrs: Set[str] = set()
            concurrent = model.concurrent(fs.key)
            for acc in fs.accesses:
                key = (scan.modname, fs.cls, acc.attr)
                spec = guarded.get(key)
                if spec is not None and spec[0] == "loop" and fs.is_async:
                    d12_attrs.add(acc.attr)
                elif concurrent and key in model.shared_attrs \
                        and fs.is_async:
                    d12_attrs.add(acc.attr)
            if d12_attrs:
                _check_atomicity(scan, fs, d12_attrs, out)

            if not fs.is_async or not concurrent:
                continue  # sync frames are event-loop atomic
            seen_lines: Set[Tuple[str, int]] = set()
            for acc in fs.accesses:
                key = (scan.modname, fs.cls, acc.attr)
                spec = guarded.get(key)
                if spec is not None and spec[0] != "loop":
                    want = _norm_spec(spec[2], fs.cls, spec[0])
                    if want is not None and want not in acc.locks:
                        if (acc.attr, acc.line) in seen_lines or \
                                _suppressed(scan.ms, acc.line, "DL013"):
                            continue
                        seen_lines.add((acc.attr, acc.line))
                        out.append(Violation(
                            scan.ms.path, acc.line, acc.col, "DL013",
                            d13_name,
                            f"{d13_summary}: `self.{acc.attr}` is "
                            f"`# guarded-by: {spec[0]}` but this async "
                            f"frame touches it without the lock",
                            _scope_of(fs.key)))
                elif spec is None and acc.kind in ("store", "mut") \
                        and key in model.shared_attrs:
                    want_any = mut_locks.get(key, set())
                    if want_any and not (acc.locks & want_any):
                        if (acc.attr, acc.line) in seen_lines or \
                                _suppressed(scan.ms, acc.line, "DL013"):
                            continue
                        seen_lines.add((acc.attr, acc.line))
                        locks = "/".join(sorted(
                            w.split(":", 1)[1] for w in want_any))
                        out.append(Violation(
                            scan.ms.path, acc.line, acc.col, "DL013",
                            d13_name,
                            f"{d13_summary}: `self.{acc.attr}` is "
                            f"mutated under `{locks}` elsewhere but "
                            f"without it here — annotate it "
                            f"`# guarded-by:` and pick one discipline",
                            _scope_of(fs.key)))
    return out


# ----------------------------------------------------------------- DL014

def check_lock_order(scans: Sequence[ModuleScan],
                     graph: CallGraph) -> List[Violation]:
    """Collect lock acquisition orders (lexical nesting + one call level
    deep while holding a lock) and flag inverted pairs."""
    # transitive acquires, bounded: direct + callees' direct
    direct: Dict[str, Set[str]] = {}
    fscans: Dict[str, FuncScan] = {}
    for scan in scans:
        for fs in scan.funcs.values():
            fscans[fs.key] = fs
            direct[fs.key] = set(fs.acquires)
    trans: Dict[str, Set[str]] = {k: set(v) for k, v in direct.items()}
    for _ in range(3):
        changed = False
        for fi in graph.functions.values():
            acc = trans.get(fi.key)
            if acc is None:
                continue
            for cs in fi.calls:
                sub = trans.get(cs.target) if cs.target else None
                if sub and not sub <= acc:
                    acc |= sub
                    changed = True
        if not changed:
            break

    # ordered pairs with one representative site each
    pairs: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for scan in scans:
        for fs in sorted(scan.funcs.values(), key=lambda f: f.key):
            for held, got, line in fs.orders:
                pairs.setdefault((held, got),
                                 (scan.ms.path, line, _scope_of(fs.key)))
            fi = graph.functions.get(fs.key)
            if fi is None:
                continue
            # calls made under a lock into functions acquiring others:
            # lexical nesting can't see these, the callgraph can. Join
            # the scan's held-lock context to the resolved call edge by
            # (line, raw callee).
            targets = {(cs.line, cs.raw): cs.target for cs in fi.calls
                       if cs.target}
            for raw, locks, line in fs.calls_under_lock:
                target = targets.get((line, raw))
                sub = trans.get(target) if target else None
                if not sub:
                    continue
                for held in sorted(locks):
                    for got in sorted(sub):
                        if held != got:
                            pairs.setdefault(
                                (held, got),
                                (scan.ms.path, line, _scope_of(fs.key)))

    name, summary = RULES["DL014"]
    out: List[Violation] = []
    scan_by_path = {scan.ms.path: scan for scan in scans}
    for (a, b), (path, line, scope) in sorted(pairs.items()):
        if a >= b or (b, a) not in pairs:
            continue  # report each inverted pair once per direction
        rpath, rline, rscope = pairs[(b, a)]
        for p, ln, sc, first, second, op, ol in (
                (path, line, scope, a, b, rpath, rline),
                (rpath, rline, rscope, b, a, path, line)):
            scan = scan_by_path.get(p)
            if scan is not None and _suppressed(scan.ms, ln, "DL014"):
                continue
            out.append(Violation(
                p, ln, 0, "DL014", name,
                f"{summary}: `{first.split(':', 1)[1]}` then "
                f"`{second.split(':', 1)[1]}` here, but the opposite "
                f"order at {op}:{ol}", sc))
    return out


# --------------------------------------------------- DL005 interprocedural

@dataclass
class _SyncPath:
    depth: int
    chain: List[str]
    sink_path: str
    sink_line: int
    what: str


def check_transitive_host_sync(graph: CallGraph,
                               max_depth: int = DEFAULT_DL008_DEPTH
                               ) -> List[Violation]:
    """Interprocedural DL005: a host-sync primitive reached from an
    engine hot-path function through sync helpers fires at the hot
    function's call site. ``HOT_SYNC_ALLOWLIST`` qualnames are excluded
    both as hot origins and as sanctioned callees/sinks."""
    reach: Dict[str, _SyncPath] = {}
    for fi in graph.functions.values():
        if fi.is_async or not fi.host_sync \
                or fi.qualname in HOT_SYNC_ALLOWLIST:
            continue
        line, what = fi.host_sync[0]
        reach[fi.key] = _SyncPath(0, [fi.key], fi.path, line, what)
    changed = True
    while changed:
        changed = False
        for fi in graph.functions.values():
            if fi.is_async or fi.qualname in HOT_SYNC_ALLOWLIST:
                continue
            for cs in fi.calls:
                sub = reach.get(cs.target) if cs.target else None
                if sub is None:
                    continue
                callee = graph.functions.get(cs.target)
                if callee is None or callee.is_async \
                        or callee.qualname in HOT_SYNC_ALLOWLIST:
                    continue
                depth = sub.depth + 1
                cur = reach.get(fi.key)
                if depth <= max_depth and \
                        (cur is None or depth < cur.depth):
                    reach[fi.key] = _SyncPath(
                        depth, [fi.key] + sub.chain,
                        sub.sink_path, sub.sink_line, sub.what)
                    changed = True

    name, summary = RULES["DL005"]
    out: List[Violation] = []
    seen: Set[Tuple[str, str]] = set()
    for fi in graph.functions.values():
        if _HOT_PATH_MARKER not in fi.path.replace("\\", "/"):
            continue
        if not HOT_FRAME_RE.search(fi.name) \
                or fi.qualname in HOT_SYNC_ALLOWLIST:
            continue
        mod = graph.modules[fi.module]
        for cs in fi.calls:
            sub = reach.get(cs.target) if cs.target else None
            if sub is None or cs.target == fi.key:
                continue
            callee = graph.functions.get(cs.target)
            if callee is not None and HOT_FRAME_RE.search(callee.name):
                continue  # hot callees carry their own per-file duty
            if (fi.key, cs.target) in seen:
                continue
            seen.add((fi.key, cs.target))
            suppressed = False
            for probe in (cs.line, cs.line - 1):
                tags = mod.suppressed.get(probe)
                if tags and ({"DL005", name, "all"} & tags):
                    suppressed = True
            if suppressed:
                continue
            chain = " -> ".join(
                k.split(":", 1)[1] for k in sub.chain)
            out.append(Violation(
                fi.path, cs.line, cs.col, "DL005", name,
                f"{summary}: `{cs.raw}` reaches host sync {sub.what} via "
                f"{chain} ({sub.sink_path}:{sub.sink_line})",
                fi.qualname))
    return out


# ------------------------------------------------------------------ driver

def analyze_races(sources: Sequence[ModuleSource],
                  graph: Optional[CallGraph] = None,
                  model_out: Optional[dict] = None) -> List[Violation]:
    """Run the dynarace passes (DL012/DL013/DL014 + interprocedural
    DL005) over already-loaded modules. Pass ``model_out={}`` to receive
    the built :class:`RaceModel` under key ``"model"`` (dot export)."""
    if graph is None:
        graph = CallGraph.build(sources)
    scans = scan_modules(sources)
    model = build_race_model(graph, scans)
    if model_out is not None:
        model_out["model"] = model
    out: List[Violation] = []
    out.extend(check_races(scans, model))
    out.extend(check_lock_order(scans, graph))
    out.extend(check_transitive_host_sync(graph))
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out
