"""dynalint CLI.

    python -m tools.dynalint --all
    python -m tools.dynalint [--baseline FILE] [--json] paths...

Runs the per-file rules (DL001-DL007, DL011) AND the whole-program
passes — dynaflow (DL008 call-graph blocking propagation, DL009/DL010
wire-schema conformance), dynarace (DL012-DL014 concurrency rules +
interprocedural DL005), dynajit (DL015-DL017 compilation-stability /
device-residency rules), dynaproto
(DL019/DL020 lifecycle-protocol conformance + the explicit-state model
checker over the declared machines, DL021 typed-error-swallow),
dynahot (DL022-DL024 hot-path cost + unbounded-growth rules over the
HOT_ROOTS reachability regions) and dynaform (DL025-DL027 dtype
promotion, warmup/serving call-form equivalence — which subsumes the
old dynajit warmup-coverage check — and the int8 tier contract) — over
one shared parse of the tree.
``--all`` is the CI spelling: the default tree, every pass; its
``--json`` carries a ``protocols`` block with the per-machine
state-space counts the model checker explored.

``--changed`` is the pre-commit spelling: per-file rules run only on
files ``git diff --name-only HEAD`` touches, while the whole-program
passes still see the full tree (a callgraph built from a diff would
miss the cross-file edges that make them sound).

Exit status: 0 when every violation is baselined (stale baseline
entries still warn on stderr), 1 when new violations exist.

Tooling extras:
    --callgraph-dot graph.dot   Graphviz export of the project call
                                graph: async defs, blocking reach,
                                concurrency roots and shared-state
                                touchers annotated
    --proto-dot machines.dot    Graphviz export of every declared
                                lifecycle machine with anchored-edge
                                coverage coloring (green = anchored,
                                red = drifted)
    --wire-schemas FILE         regenerate docs/wire_schemas.md from the
                                runtime/wire.py registry
    --write-env-docs FILE       regenerate docs/env_vars.md
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .analyzer import RULES, load_sources
from .baseline import apply_baseline, load_baseline
from .callgraph import DEFAULT_DL008_DEPTH, CallGraph
from .dynaflow import analyze_tree

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.txt")
DEFAULT_PATHS = ["dynamo_tpu", "bench.py", "tools"]


def _git_changed_py(repo_root: str) -> list:
    """Absolute paths of .py files `git diff --name-only HEAD` reports
    (staged + unstaged). Deleted files drop out (no file to lint)."""
    import subprocess

    try:
        raw = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=repo_root, capture_output=True, text=True, timeout=30,
            check=True).stdout
    except Exception as e:  # not a git checkout / git missing
        print(f"dynalint --changed: git diff failed ({e}); "
              f"running per-file rules on the full tree", file=sys.stderr)
        return None
    out = []
    for line in raw.splitlines():
        line = line.strip()
        if line.endswith(".py"):
            ab = os.path.join(repo_root, line)
            if os.path.exists(ab):
                out.append(ab)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dynalint",
        description="project-native async/JAX static analysis")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to scan (default: {DEFAULT_PATHS})")
    ap.add_argument("--all", action="store_true", dest="run_all",
                    help="run every pass (per-file + dynaflow + dynarace) "
                         "over the default tree off one shared AST parse "
                         "cache — the CI entry point")
    ap.add_argument("--changed", action="store_true",
                    help="incremental mode: per-file rules only on files "
                         "`git diff --name-only HEAD` reports; "
                         "whole-program passes still run over the full "
                         "tree (the pre-commit entry point)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="grandfathered-violations file "
                         "(default: tools/dynalint/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, ignoring the baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline file to grandfather every "
                         "current violation (ratchet reset — review the "
                         "diff before committing)")
    ap.add_argument("--write-env-docs", metavar="PATH", default=None,
                    help="regenerate the env-var reference (docs/"
                         "env_vars.md) from the runtime/config.py registry")
    ap.add_argument("--wire-schemas", metavar="PATH", default=None,
                    help="regenerate the wire-frame reference (docs/"
                         "wire_schemas.md) from the runtime/wire.py "
                         "registry")
    ap.add_argument("--callgraph-dot", metavar="PATH", default=None,
                    help="write a Graphviz export of the project call "
                         "graph (async defs filled, blocking reach in "
                         "red) and exit")
    ap.add_argument("--proto-dot", metavar="PATH", default=None,
                    help="write a Graphviz export of every declared "
                         "lifecycle machine (runtime/proto.py) with "
                         "anchored-edge coverage coloring and exit")
    ap.add_argument("--dl008-depth", type=int, default=DEFAULT_DL008_DEPTH,
                    help="max sync call frames between an async def and a "
                         "blocking primitive for DL008 (default %(default)s)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, (name, summary) in sorted(RULES.items()):
            print(f"{code}  {name:28s} {summary}")
        return 0

    if args.write_env_docs:
        sys.path.insert(0, REPO_ROOT)
        from dynamo_tpu.runtime.config import render_env_docs

        with open(args.write_env_docs, "w", encoding="utf-8") as f:
            f.write(render_env_docs())
        print(f"wrote {args.write_env_docs}")
        return 0

    if args.wire_schemas:
        sys.path.insert(0, REPO_ROOT)
        from dynamo_tpu.runtime.wire import render_wire_docs

        with open(args.wire_schemas, "w", encoding="utf-8") as f:
            f.write(render_wire_docs())
        print(f"wrote {args.wire_schemas}")
        return 0

    if args.run_all:
        paths = [os.path.join(REPO_ROOT, p) for p in DEFAULT_PATHS]
    else:
        paths = args.paths or [os.path.join(REPO_ROOT, p)
                               for p in DEFAULT_PATHS]

    per_file_paths = None
    if args.changed:
        per_file_paths = _git_changed_py(REPO_ROOT)
        if per_file_paths is not None and not args.as_json:
            print(f"--changed: per-file rules on {len(per_file_paths)} "
                  f"file(s); whole-program passes on the full tree",
                  file=sys.stderr)

    if args.callgraph_dot:
        from .dynahot import hot_regions
        from .dynarace import analyze_races

        sources = load_sources(paths, root=REPO_ROOT)
        graph = CallGraph.build(sources)
        # concurrency coloring: roots bold orange, shared-state-touching
        # functions double-bordered (see dynarace.build_race_model);
        # dynahot regions shaded by accumulated loop depth
        model_out: dict = {}
        analyze_races(sources, graph=graph, model_out=model_out)
        hot = hot_regions(graph, sources)
        with open(args.callgraph_dot, "w", encoding="utf-8") as f:
            f.write(graph.to_dot(race=model_out.get("model"), hot=hot))
        print(f"wrote {args.callgraph_dot} "
              f"({len(graph.functions)} functions, {len(hot)} hot)")
        return 0

    if args.proto_dot:
        from .dynaproto import analyze_protocols, protocols_to_dot

        sources = load_sources(paths, root=REPO_ROOT)
        anchors_out: dict = {}
        analyze_protocols(sources, anchors_out=anchors_out)
        schemas = anchors_out.get("schemas") or {}
        with open(args.proto_dot, "w", encoding="utf-8") as f:
            f.write(protocols_to_dot(schemas,
                                     anchors_out.get("anchors") or []))
        print(f"wrote {args.proto_dot} ({len(schemas)} machines)")
        return 0

    t0 = time.perf_counter()
    timings: dict = {}
    proto_report: dict = {}
    violations = analyze_tree(paths, root=REPO_ROOT,
                              dl008_depth=args.dl008_depth,
                              timings=timings,
                              proto_report=proto_report,
                              per_file_paths=per_file_paths)
    wall = time.perf_counter() - t0

    if args.write_baseline:
        lines = ["# dynalint baseline — grandfathered violations "
                 "(ratchet-only: fix, don't add)",
                 "# format: path::rule-name::scope  "
                 "(one line per allowed instance)"]
        lines += sorted(v.baseline_key for v in violations)
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {len(violations)} entries to {args.baseline}")
        return 0

    stale: list = []
    if not args.no_baseline and os.path.exists(args.baseline):
        allowed = load_baseline(args.baseline)
        violations, stale = apply_baseline(violations, allowed)

    if args.as_json:
        rule_counts: dict = {}
        for v in violations:
            rule_counts[v.code] = rule_counts.get(v.code, 0) + 1
        print(json.dumps({"violations": [v.to_dict() for v in violations],
                          "stale_baseline": stale,
                          "wall_seconds": round(wall, 3),
                          "rule_counts": dict(sorted(rule_counts.items())),
                          "passes": timings,
                          "protocols": proto_report}, indent=2))
    else:
        for v in violations:
            print(v.render())
        for key in stale:
            print(f"warning: stale baseline entry (violation fixed — "
                  f"delete the line): {key}", file=sys.stderr)
        if violations:
            print(f"\n{len(violations)} new violation(s). Fix them, "
                  f"suppress with `# dynalint: disable=<rule>`, or (last "
                  f"resort, justified) add to {args.baseline}",
                  file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
