"""dynaproto: static lifecycle-protocol conformance (DL019-DL021).

The protocol registry in ``dynamo_tpu/runtime/proto.py`` declares each
failure-handling state machine once as a pure literal (same contract as
PR 5's wire registry: this pass parses the file with ``ast.literal_eval``
and never imports the runtime package). Code sites *anchor* their
transitions either with a call::

    proto.step("breaker", "open", "half_open")

or with a comment on the mutation line (or the line directly above)::

    self.state = BREAKER_OPEN   # proto: breaker closed|half_open->open

``|`` separates alternative states (the full cross product must be
declared); ``,`` separates several transitions in one anchor.

Rules, all tier-1-enforced with an EMPTY baseline:

- **DL019 undeclared-transition** — an anchor naming an unknown machine,
  an unknown state, or a (from, to) pair that is not a declared edge;
  and a store to a declared protocol-state attribute (the machine's
  ``owners`` list) outside ``__init__`` that carries no anchor: every
  protocol-state mutation must say which declared edge it implements.
- **DL020 unreachable/missing-coverage** — a declared edge no code site
  anchors (the model and the code have drifted); an edge declared out
  of a terminal state (flagged at the registration); and — via
  dynarace's concurrency-root inference — an anchored transition
  reachable from ≥2 concurrent roots that breaks the machine's declared
  ``lock`` discipline (``"loop"``: the anchored statement must not
  straddle an ``await``; ``"self.<attr>"``: the anchor must hold that
  lock). Model-checker invariant violations (``modelcheck.py``) are
  also reported under this code, at the machine's registration line.
- **DL021 typed-error-swallow** — a broad ``except Exception`` /
  ``except BaseException`` on a path reachable from an HTTP handler or
  a ``ServeHandle`` whose try body awaits, with no re-raise, no earlier
  typed clause, and no mention of the typed guard errors
  (``DeadlineExceeded``, ``NoCapacity``, ``NoRespondersError``) in the
  handler: those must reach the 504/503 mappers, never collapse into a
  generic 500.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .analyzer import RULES, ModuleSource, Violation, dotted
from .callgraph import CallGraph

PROTO_MODULE_REL = "dynamo_tpu/runtime/proto.py"

# comment anchor:  # proto: <machine> <from>[|<from>...]-><to>[|<to>...]
#                  [, <from>-><to> ...]
PROTO_COMMENT_RE = re.compile(r"#\s*proto:\s*([\w.\-]+)\s+([^#]+)")
_TRANSITION_RE = re.compile(
    r"^\s*([\w|]+)\s*->\s*([\w|]+)\s*$")

# DL021: the typed guard errors that must reach the HTTP error mappers,
# plus the broader names whose presence in an earlier except clause or
# the handler body proves the typed path is handled before/inside the
# broad catch.
TYPED_GUARD_ERRORS = frozenset({
    "DeadlineExceeded", "NoCapacity", "NoRespondersError"})
TYPED_HANDLED_NAMES = TYPED_GUARD_ERRORS | frozenset({
    "TimeoutError", "CancelledError"})


# ------------------------------------------------------------------ schemas

@dataclass(frozen=True)
class ProtoSchema:
    """Statically-extracted twin of runtime ``proto.ProtoMachine``."""

    name: str
    states: Tuple[str, ...]
    initial: str
    terminal: Tuple[str, ...]
    lock: Optional[str]
    owners: Tuple[Tuple[str, str], ...]
    edges: Tuple[dict, ...]               # normalized edge dicts
    vars: Tuple[Tuple[str, tuple], ...]
    init: Tuple[Tuple[str, object], ...]
    env: Tuple[dict, ...]
    invariants: Tuple[dict, ...]
    depth: int
    line: int                             # registration line
    const: str                            # bound module constant

    @property
    def edge_pairs(self) -> frozenset:
        return frozenset((e["from"], e["to"]) for e in self.edges)


def _norm_schema_edge(e: dict, env: bool = False) -> dict:
    when = {}
    for k, v in (e.get("when") or {}).items():
        when[k] = tuple(v) if isinstance(v, (tuple, list)) else (v,)
    return {
        "from": "" if env else e["from"], "to": "" if env else e["to"],
        "name": e.get("name") or f"{e.get('from')}->{e.get('to')}",
        "when": when, "set": dict(e.get("set") or {}),
        "doc": e.get("doc", "")}


def load_protocols(ms: ModuleSource
                   ) -> Tuple[Dict[str, ProtoSchema], List[Violation]]:
    """Parse ``register_protocol`` declarations out of the proto module.
    Non-literal declarations fail loudly (they would silently fall out
    of the static pass); structural errors (edges out of terminal
    states, undeclared states) are DL020 at the registration line."""
    schemas: Dict[str, ProtoSchema] = {}
    bad: List[Violation] = []
    d19, d20 = RULES["DL019"][0], RULES["DL020"][0]
    for node in ms.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "register_protocol"):
            continue
        const = node.targets[0].id
        call = node.value
        try:
            name = ast.literal_eval(call.args[0])
            kw = {k.arg: ast.literal_eval(k.value) for k in call.keywords}
        except (ValueError, SyntaxError):
            bad.append(Violation(
                ms.path, node.lineno, node.col_offset, "DL019", d19,
                f"register_protocol({const}) uses non-literal arguments: "
                f"the static conformance pass cannot see this machine",
                "<module>"))
            continue
        states = tuple(kw.get("states", ()))
        terminal = tuple(kw.get("terminal", ()))
        edges = tuple(_norm_schema_edge(e) for e in kw.get("edges", ()))
        for e in edges:
            if e["from"] not in states or e["to"] not in states:
                bad.append(Violation(
                    ms.path, node.lineno, 0, "DL019", d19,
                    f"machine `{name}` edge `{e['name']}` uses "
                    f"undeclared state(s) "
                    f"`{e['from']}`->`{e['to']}`", name))
            elif e["from"] in terminal:
                bad.append(Violation(
                    ms.path, node.lineno, 0, "DL020", d20,
                    f"machine `{name}` edge `{e['name']}` leaves "
                    f"terminal state `{e['from']}`", name))
        schemas[name] = ProtoSchema(
            name=name, states=states,
            initial=kw.get("initial", ""), terminal=terminal,
            lock=kw.get("lock"),
            owners=tuple((str(m), str(a))
                         for m, a in kw.get("owners", ())),
            edges=edges,
            vars=tuple(sorted((k, tuple(v)) for k, v in
                              (kw.get("vars") or {}).items())),
            init=tuple(sorted((kw.get("init") or {}).items())),
            env=tuple(_norm_schema_edge(e, env=True)
                      for e in kw.get("env", ())),
            invariants=tuple(dict(i) for i in kw.get("invariants", ())),
            depth=int(kw.get("depth", 64)),
            line=node.lineno, const=const)
    return schemas, bad


# ------------------------------------------------------------------ anchors

@dataclass
class Anchor:
    """One code site declaring protocol transitions."""

    machine: str
    transitions: List[Tuple[str, str]]    # (from, to) cross product
    path: str
    line: int
    func_key: Optional[str]               # '<module>:<qualname>' or None
    kind: str                             # 'call' | 'comment'
    raw: str = ""
    has_await: bool = False               # statement straddles an await
    locks: frozenset = frozenset()        # normalized lock ids held


@dataclass
class OwnerStore:
    """A store to a declared protocol-state attribute."""

    machine: str
    attr: str
    path: str
    line: int
    scope: str


@dataclass
class _ProtoScanOut:
    anchors: List[Anchor] = field(default_factory=list)
    stores: List[OwnerStore] = field(default_factory=list)
    bad: List[Violation] = field(default_factory=list)


def _parse_comment_anchor(text: str
                          ) -> Optional[Tuple[str, List[Tuple[str, str]],
                                              List[str]]]:
    """Parse the transitions of one comment anchor. Returns
    (machine, [(from, to), ...], errors); None when the line carries no
    anchor at all."""
    m = PROTO_COMMENT_RE.search(text)
    if m is None:
        return None
    machine = m.group(1)
    body = m.group(2).strip()
    transitions: List[Tuple[str, str]] = []
    errors: List[str] = []
    for part in (p.strip() for p in body.split(",") if p.strip()):
        tm = _TRANSITION_RE.match(part)
        if tm is None:
            errors.append(f"malformed transition {part!r} "
                          f"(want from[|from]->to[|to])")
            continue
        froms = [s for s in tm.group(1).split("|") if s]
        tos = [s for s in tm.group(2).split("|") if s]
        for f in froms:
            for t in tos:
                transitions.append((f, t))
    return machine, transitions, errors


class _AnchorScan(ast.NodeVisitor):
    """Collect call anchors, owner-attribute stores and per-statement
    await/lock context for one module."""

    def __init__(self, ms: ModuleSource, schemas: Dict[str, ProtoSchema],
                 modname: str):
        from .analyzer import LOCK_NAME_RE

        self.ms = ms
        self.schemas = schemas
        self.modname = modname
        self.out = _ProtoScanOut()
        self._lock_re = LOCK_NAME_RE
        self._classes: List[str] = []
        self._funcs: List[str] = []
        self._locks: List[str] = []
        self._step_imported = False   # `from ...proto import step`
        # lines whose enclosing statement contains an Await
        self._await_lines: Set[int] = set()
        for node in ast.walk(ms.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.Expr, ast.Return)):
                if any(isinstance(sub, ast.Await) for sub in ast.walk(node)):
                    end = getattr(node, "end_lineno", node.lineno)
                    self._await_lines.update(range(node.lineno, end + 1))
        # lexical lock extents, for attributing held locks to comment
        # anchors (call anchors use the live stack instead)
        self.lock_spans: List[Tuple[int, int, str]] = []
        for node in ast.walk(ms.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id(item.context_expr)
                    if lid is not None:
                        self.lock_spans.append(
                            (node.lineno,
                             getattr(node, "end_lineno", node.lineno), lid))
        # machine owner lookup for this module: attr -> machine name
        norm = ms.path.replace("\\", "/")
        self._owner_attrs: Dict[str, str] = {}
        for schema in schemas.values():
            for mod_suffix, attr in schema.owners:
                if norm.endswith(mod_suffix):
                    self._owner_attrs[attr] = schema.name

    def locks_at(self, line: int) -> frozenset:
        return frozenset(lid for lo, hi, lid in self.lock_spans
                         if lo <= line <= hi)

    # ------------------------------------------------------------- scoping

    def _scope(self) -> str:
        parts = self._classes + self._funcs
        return ".".join(parts) if parts else "<module>"

    def _func_key(self) -> Optional[str]:
        if not (self._classes or self._funcs):
            return None
        return f"{self.modname}:{self._scope()}"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    def _visit_func(self, node) -> None:
        self._funcs.append(node.name)
        self.generic_visit(node)
        self._funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if (node.module or "").endswith("proto") or node.level:
            for alias in node.names:
                if alias.name == "step" and alias.asname is None:
                    self._step_imported = True

    # --------------------------------------------------------------- locks

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            expr = expr.func
        d = dotted(expr)
        if d is None or not self._lock_re.search(d.rsplit(".", 1)[-1]):
            return None
        if d.startswith("self.") and self._classes:
            return f"self.{d[5:]}"
        return d

    def _visit_with(self, node) -> None:
        acquired = [lid for item in node.items
                    if (lid := self._lock_id(item.context_expr))]
        self._locks.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self._locks.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # --------------------------------------------------------------- sites

    def _mk_anchor(self, machine: str, transitions, node: ast.AST,
                   kind: str, raw: str = "") -> Anchor:
        return Anchor(
            machine=machine, transitions=list(transitions),
            path=self.ms.path, line=node.lineno,
            func_key=self._func_key(), kind=kind, raw=raw,
            has_await=node.lineno in self._await_lines,
            locks=frozenset(self._locks))

    def _is_step_call(self, node: ast.Call) -> bool:
        """``proto.step(...)`` (any alias whose dotted base ends in
        `proto`) or a bare ``step(...)`` imported from the proto
        module — never an unrelated `.step()` method."""
        d = dotted(node.func)
        if d is None:
            return False
        if d == "step":
            return self._step_imported
        parts = d.split(".")
        return parts[-1] == "step" and parts[-2].endswith("proto")

    def visit_Call(self, node: ast.Call) -> None:
        """``proto.step("machine", frm, to)`` call anchors; frm may be a
        string or a tuple of strings (all pairs must be declared)."""
        if self._is_step_call(node) and len(node.args) >= 3:
            try:
                machine = ast.literal_eval(node.args[0])
                frm = ast.literal_eval(node.args[1])
                to = ast.literal_eval(node.args[2])
            except (ValueError, SyntaxError):
                machine = None
            if isinstance(machine, str):
                froms = [frm] if isinstance(frm, str) else list(frm)
                self.out.anchors.append(self._mk_anchor(
                    machine, [(f, to) for f in froms], node, "call",
                    raw=ast.unparse(node.func)))
        self.generic_visit(node)

    def _store_target(self, t: ast.AST) -> None:
        if isinstance(t, ast.Attribute) and isinstance(t.ctx, ast.Store):
            machine = self._owner_attrs.get(t.attr)
            if machine is not None and "__init__" not in self._funcs:
                self.out.stores.append(OwnerStore(
                    machine=machine, attr=t.attr, path=self.ms.path,
                    line=t.lineno, scope=self._scope()))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._store_target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._store_target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._store_target(node.target)
        self.generic_visit(node)


def _comment_anchors(ms: ModuleSource, schemas: Dict[str, ProtoSchema],
                     scan: "_AnchorScan") -> Tuple[List[Anchor],
                                                   List[Violation]]:
    """Comment anchors, found via ``tokenize`` so `# proto:` examples
    inside docstrings never count. A trailing comment binds to its own
    (code) line; a standalone comment line binds to the line below."""
    import io
    import tokenize

    d19 = RULES["DL019"][0]
    anchors: List[Anchor] = []
    bad: List[Violation] = []
    if "proto:" not in ms.src:
        return anchors, bad
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(ms.src).readline))
    except tokenize.TokenizeError:
        return anchors, bad
    lines = ms.src.splitlines()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        parsed = _parse_comment_anchor(tok.string)
        if parsed is None:
            continue
        machine, transitions, errors = parsed
        i = tok.start[0]
        for err in errors:
            bad.append(Violation(
                ms.path, i, 0, "DL019", d19,
                f"{RULES['DL019'][1]}: {err}", "<module>"))
        standalone = not lines[i - 1][:tok.start[1]].strip()
        code_line = i + 1 if standalone else i
        anchors.append(Anchor(
            machine=machine, transitions=transitions, path=ms.path,
            line=i, func_key=None,
            kind="comment", raw=tok.string.strip(),
            has_await=code_line in scan._await_lines,
            locks=scan.locks_at(code_line)))
    return anchors, bad


def _attribute_comment_scopes(ms: ModuleSource, modname: str,
                              anchors: List[Anchor]) -> None:
    """Fill func_key for comment anchors by walking the AST's function
    extents (lineno..end_lineno)."""
    if not anchors:
        return
    spans: List[Tuple[int, int, str]] = []

    def walk(node, classes: List[str], funcs: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, classes + [child.name], funcs)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = ".".join(classes + funcs + [child.name])
                spans.append((child.lineno,
                              getattr(child, "end_lineno", child.lineno),
                              qual))
                walk(child, classes, funcs + [child.name])
            else:
                walk(child, classes, funcs)

    walk(ms.tree, [], [])
    for a in anchors:
        best: Optional[Tuple[int, str]] = None
        for lo, hi, qual in spans:
            if lo <= a.line <= hi:
                if best is None or lo > best[0]:
                    best = (lo, qual)
        if best is not None:
            a.func_key = f"{modname}:{best[1]}"


def collect_anchors(sources: Sequence[ModuleSource],
                    schemas: Dict[str, ProtoSchema]
                    ) -> Tuple[List[Anchor], List[OwnerStore],
                               List[Violation]]:
    """Every anchor (call + comment) and owner-attribute store in the
    scanned tree, plus malformed-anchor violations."""
    from .callgraph import module_name

    anchors: List[Anchor] = []
    stores: List[OwnerStore] = []
    bad: List[Violation] = []
    for ms in sources:
        modname = module_name(ms.path)
        scan = _AnchorScan(ms, schemas, modname)
        scan.visit(ms.tree)
        canchors, cbad = _comment_anchors(ms, schemas, scan)
        _attribute_comment_scopes(ms, modname, canchors)
        anchors.extend(scan.out.anchors)
        anchors.extend(canchors)
        stores.extend(scan.out.stores)
        bad.extend(scan.out.bad)
        bad.extend(cbad)
    return anchors, stores, bad


# ------------------------------------------------------------------- DL019

def _suppressed(ms: ModuleSource, line: int, code: str) -> bool:
    name = RULES[code][0]
    for probe in (line, line - 1):
        tags = ms.suppressed.get(probe)
        if tags and (code in tags or name in tags or "all" in tags):
            return True
    return False


def check_transitions(sources: Sequence[ModuleSource],
                      schemas: Dict[str, ProtoSchema],
                      anchors: List[Anchor],
                      stores: List[OwnerStore]) -> List[Violation]:
    """DL019: anchors must name declared machines/states/edges; owner
    stores must be anchored."""
    out: List[Violation] = []
    name, summary = RULES["DL019"]
    by_path = {ms.path: ms for ms in sources}

    for a in anchors:
        ms = by_path.get(a.path)
        if ms is not None and _suppressed(ms, a.line, "DL019"):
            continue
        schema = schemas.get(a.machine)
        if schema is None:
            out.append(Violation(
                a.path, a.line, 0, "DL019", name,
                f"{summary}: anchor names unknown machine "
                f"`{a.machine}`", a.machine))
            continue
        for frm, to in a.transitions:
            if frm not in schema.states or to not in schema.states:
                out.append(Violation(
                    a.path, a.line, 0, "DL019", name,
                    f"{summary}: anchor on `{a.machine}` names unknown "
                    f"state in `{frm}`->`{to}`", a.machine))
            elif (frm, to) not in schema.edge_pairs:
                out.append(Violation(
                    a.path, a.line, 0, "DL019", name,
                    f"{summary}: `{frm}`->`{to}` is not a declared edge "
                    f"of `{a.machine}` — declare it in runtime/proto.py "
                    f"or fix the site", a.machine))

    # owner stores: an anchor for the owning machine on the store line
    # or the line above (comment) / same line (call)
    anchored_lines: Dict[Tuple[str, str], Set[int]] = {}
    for a in anchors:
        key = (a.path, a.machine)
        anchored_lines.setdefault(key, set()).add(a.line)
    for st in stores:
        lines = anchored_lines.get((st.path, st.machine), set())
        if st.line in lines or (st.line - 1) in lines:
            continue
        ms = by_path.get(st.path)
        if ms is not None and _suppressed(ms, st.line, "DL019"):
            continue
        out.append(Violation(
            st.path, st.line, 0, "DL019", name,
            f"{summary}: store to protocol-state attr `.{st.attr}` of "
            f"machine `{st.machine}` carries no anchor — add "
            f"`# proto: {st.machine} <from>-><to>` naming the declared "
            f"edge this mutation implements", st.scope))
    return out


# ------------------------------------------------------------------- DL020

def check_coverage(sources: Sequence[ModuleSource],
                   schemas: Dict[str, ProtoSchema],
                   anchors: List[Anchor],
                   proto_path: str,
                   stores: Optional[List[OwnerStore]] = None,
                   race_model=None) -> List[Violation]:
    """DL020: every declared edge anchored; lock discipline on anchored
    transitions (via the dynarace concurrency model when provided)."""
    out: List[Violation] = []
    name, summary = RULES["DL020"]
    by_path = {ms.path: ms for ms in sources}
    proto_ms = by_path.get(proto_path)
    # anchors that annotate an actual protocol-state mutation: the lock
    # discipline applies to THOSE (an anchored effect edge — a discovery
    # delete, a flush — is legitimately an await)
    store_lines: Set[Tuple[str, str, int]] = set()
    for st in stores or []:
        store_lines.add((st.path, st.machine, st.line))

    def _is_mutation_anchor(a: Anchor) -> bool:
        return ((a.path, a.machine, a.line) in store_lines
                or (a.path, a.machine, a.line + 1) in store_lines)

    covered: Dict[Tuple[str, str, str], int] = {}
    for a in anchors:
        if a.machine not in schemas:
            continue
        schema = schemas[a.machine]
        for pair in a.transitions:
            if pair in schema.edge_pairs:
                covered[(a.machine, pair[0], pair[1])] = \
                    covered.get((a.machine, pair[0], pair[1]), 0) + 1

    for schema in schemas.values():
        for e in schema.edges:
            if (schema.name, e["from"], e["to"]) in covered:
                continue
            if proto_ms is not None and \
                    _suppressed(proto_ms, schema.line, "DL020"):
                continue
            out.append(Violation(
                proto_path, schema.line, 0, "DL020", name,
                f"{summary}: edge `{e['name']}` "
                f"(`{e['from']}`->`{e['to']}`) of machine "
                f"`{schema.name}` has no anchoring code site — the "
                f"model and the code have drifted", schema.name))

    # lock discipline on anchored transitions
    for a in anchors:
        schema = schemas.get(a.machine)
        if schema is None:
            continue
        ms = by_path.get(a.path)
        if ms is not None and _suppressed(ms, a.line, "DL020"):
            continue
        if schema.lock == "loop":
            if a.has_await and _is_mutation_anchor(a):
                out.append(Violation(
                    a.path, a.line, 0, "DL020", name,
                    f"{summary}: machine `{a.machine}` declares "
                    f"event-loop atomicity (lock=\"loop\") but this "
                    f"anchored transition straddles an await — the "
                    f"state can be observed mid-flight",
                    a.func_key.split(":", 1)[1] if a.func_key
                    else "<module>"))
        elif schema.lock and schema.lock.startswith("self."):
            if _is_mutation_anchor(a) and schema.lock not in a.locks:
                out.append(Violation(
                    a.path, a.line, 0, "DL020", name,
                    f"{summary}: machine `{a.machine}` declares lock "
                    f"`{schema.lock}` but this anchored transition does "
                    f"not hold it",
                    a.func_key.split(":", 1)[1] if a.func_key
                    else "<module>"))
        elif schema.lock is None and race_model is not None \
                and a.func_key is not None:
            roots = race_model.func_roots.get(a.func_key, set())
            reentrant = any(race_model.roots[r].reentrant for r in roots)
            if len(roots) >= 2 or reentrant:
                out.append(Violation(
                    a.path, a.line, 0, "DL020", name,
                    f"{summary}: transition of `{a.machine}` is "
                    f"reachable from "
                    f"{'a reentrant root' if reentrant and len(roots) < 2 else f'{len(roots)} concurrent roots'} "
                    f"but the machine declares no lock — declare "
                    f"lock=\"loop\" (and keep transitions "
                    f"single-statement) or a real lock",
                    a.func_key.split(":", 1)[1]))
    return out


# ------------------------------------------------------------------- DL021

def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        d = dotted(n)
        if d in ("Exception", "BaseException"):
            return True
    return False


def _names_in(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if node is None:
        return out
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


def check_typed_error_swallow(sources: Sequence[ModuleSource],
                              graph: CallGraph) -> List[Violation]:
    """DL021 over functions reachable from the HTTP handler plane or
    ServeHandle. Roots: aiohttp route handlers registered in llm/http
    modules + every ServeHandle method."""
    name, summary = RULES["DL021"]
    roots: Set[str] = set()
    for fi in graph.functions.values():
        norm = fi.path.replace("\\", "/")
        if "llm/http/" in norm:
            for hr in fi.handler_refs:
                if hr.target:
                    roots.add(hr.target)
        if norm.endswith("runtime/component.py") and \
                fi.qualname.startswith("ServeHandle."):
            roots.add(fi.key)

    reached: Set[str] = set(roots)
    stack = list(roots)
    while stack:
        fi = graph.functions.get(stack.pop())
        if fi is None:
            continue
        for cs in fi.calls:
            if cs.target and cs.target in graph.functions \
                    and cs.target not in reached:
                reached.add(cs.target)
                stack.append(cs.target)

    # function key -> (module, function extent) for locating handlers;
    # a nested def's body is walked from its enclosing function too, so
    # dedupe findings by (path, line)
    out: List[Violation] = []
    seen_sites: Set[Tuple[str, int]] = set()
    by_mod: Dict[str, ModuleSource] = {ms.path: ms for ms in sources}
    for key in sorted(reached):
        fi = graph.functions[key]
        ms = by_mod.get(fi.path)
        if ms is None:
            continue
        fnode = _find_func_node(ms.tree, fi)
        if fnode is None:
            continue
        for node in ast.walk(fnode):
            if not isinstance(node, ast.Try):
                continue
            # only try bodies that await can raise the typed guard
            # errors (they surface from bounded waits / routed hops)
            body_awaits = any(
                isinstance(sub, ast.Await)
                for stmt in node.body for sub in ast.walk(stmt))
            if not body_awaits:
                continue
            earlier: Set[str] = set()
            for handler in node.handlers:
                if not _handler_is_broad(handler):
                    earlier |= _names_in(handler.type)
                    continue
                if earlier & TYPED_HANDLED_NAMES:
                    break  # typed errors peeled off before the broad catch
                if any(isinstance(sub, ast.Raise)
                       for sub in ast.walk(handler)):
                    break  # re-raises (conditionally or not)
                body_names: Set[str] = set()
                for stmt in handler.body:
                    body_names |= _names_in(stmt)
                if body_names & TYPED_GUARD_ERRORS:
                    break  # maps/branches on the typed errors inline
                if _suppressed(ms, handler.lineno, "DL021"):
                    break
                if (fi.path, handler.lineno) in seen_sites:
                    break
                seen_sites.add((fi.path, handler.lineno))
                out.append(Violation(
                    fi.path, handler.lineno, handler.col_offset,
                    "DL021", name,
                    f"{summary}: broad except on an awaiting try body "
                    f"reachable from the HTTP/ServeHandle plane (via "
                    f"`{fi.qualname}`) — peel off "
                    f"DeadlineExceeded/NoCapacity/NoRespondersError "
                    f"first or re-raise them", fi.qualname))
                break
    return out


def _find_func_node(tree: ast.AST, fi) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fi.name and node.lineno == fi.lineno:
            return node
    return None


# ------------------------------------------------------------------- driver

def analyze_protocols(sources: Sequence[ModuleSource],
                      schemas: Optional[Dict[str, ProtoSchema]] = None,
                      graph: Optional[CallGraph] = None,
                      race_model=None,
                      proto_path: str = PROTO_MODULE_REL,
                      anchors_out: Optional[dict] = None
                      ) -> List[Violation]:
    """Run the dynaproto conformance passes (DL019/DL020/DL021) over
    already-loaded modules. The protocol registry defaults to the
    scanned ``dynamo_tpu/runtime/proto.py``; pass ``schemas`` for
    fixture trees. ``anchors_out={}`` receives the collected anchors
    and schemas (the --proto-dot exporter and the --json protocols
    report reuse them)."""
    out: List[Violation] = []
    if schemas is None:
        proto_ms = next((m for m in sources if m.path == proto_path), None)
        if proto_ms is None:
            return out
        schemas, bad = load_protocols(proto_ms)
        out.extend(bad)
    if graph is None:
        graph = CallGraph.build(sources)
    anchors, stores, bad = collect_anchors(sources, schemas)
    out.extend(bad)
    out.extend(check_transitions(sources, schemas, anchors, stores))
    out.extend(check_coverage(sources, schemas, anchors, proto_path,
                              stores=stores, race_model=race_model))
    out.extend(check_typed_error_swallow(sources, graph))
    if anchors_out is not None:
        anchors_out["schemas"] = schemas
        anchors_out["anchors"] = anchors
        anchors_out["stores"] = stores
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out


# --------------------------------------------------------------- dot export

def protocols_to_dot(schemas: Dict[str, ProtoSchema],
                     anchors: Sequence[Anchor]) -> str:
    """Graphviz export of every declared machine: one cluster per
    machine, terminal states double-circled, anchored edges green with
    their site count, unanchored edges red — the drift is visible."""
    covered: Dict[Tuple[str, str, str], int] = {}
    for a in anchors:
        for frm, to in a.transitions:
            covered[(a.machine, frm, to)] = \
                covered.get((a.machine, frm, to), 0) + 1
    lines = ["digraph dynaproto {",
             '  rankdir=LR; fontname="Helvetica";',
             '  node [fontname="Helvetica"]; '
             'edge [fontname="Helvetica", fontsize=10];']
    for i, name in enumerate(sorted(schemas)):
        s = schemas[name]
        lines.append(f'  subgraph cluster_{i} {{')
        lines.append(f'    label="{name}";')
        for st in s.states:
            shape = "doublecircle" if st in s.terminal else "ellipse"
            style = ', style=bold' if st == s.initial else ""
            lines.append(f'    "{name}.{st}" [label="{st}", '
                         f'shape={shape}{style}];')
        for e in s.edges:
            n = covered.get((name, e["from"], e["to"]), 0)
            color = "forestgreen" if n else "red"
            label = f'{e["name"]} ({n})' if n else f'{e["name"]} (0!)'
            lines.append(f'    "{name}.{e["from"]}" -> '
                         f'"{name}.{e["to"]}" '
                         f'[label="{label}", color={color}];')
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines) + "\n"
