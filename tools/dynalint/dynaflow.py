"""dynaflow: the interprocedural rule passes (DL008-DL010).

Built on :mod:`callgraph` (whole-program call graph) and the wire-schema
registry declared in ``dynamo_tpu/runtime/wire.py``. The registry is read
**statically** — ``register_frame(...)`` calls are required to be pure
literals, parsed here with ``ast.literal_eval`` — so the lint pass never
imports the runtime package (no jax, no msgpack, runs anywhere).

Rules:

- **DL008 transitive-blocking-in-async** — a blocking primitive
  (``time.sleep``, ``open``, ``requests.*``, ...) reachable from an
  ``async def`` through a chain of sync project helpers, bounded by
  ``--dl008-depth`` (default 4) frames. Reported at the async def's call
  site into the chain; suppressible there or at the blocking sink line.
- **DL009 wire-field-drift** — a literal key written through a
  ``wire.checked(FRAME, ...)`` encode anchor or read through a
  ``wire.decoded(FRAME, ...)`` decode anchor that is absent from the
  frame's declared schema; plus the whole-program direction: a field
  declared *required* that no decode anchor anywhere ever reads.
- **DL010 undeclared-wire-frame** — a ``codec.encode`` /
  ``codec.encode_parts`` call site whose header is neither routed through
  ``wire.checked`` nor statically matches any registered frame. Opaque
  headers (built elsewhere) are skipped: a static pass must not guess.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .analyzer import (RULES, ModuleSource, Violation, call_attr, dotted,
                       load_sources)
from .callgraph import DEFAULT_DL008_DEPTH, CallGraph

WIRE_MODULE_REL = "dynamo_tpu/runtime/wire.py"
CODEC_MODULE_REL = "dynamo_tpu/runtime/codec.py"

_ANCHOR_ENCODE = "checked"
_ANCHOR_DECODE = "decoded"


# --------------------------------------------------------------- wire schemas

@dataclass(frozen=True)
class FrameSchema:
    """Statically-extracted twin of runtime ``wire.WireFrame``."""

    name: str
    version: int
    required: frozenset
    optional: frozenset
    when: Tuple[Tuple[str, object], ...]
    line: int          # registration line in the wire module
    const: str         # module-level constant the registration binds

    @property
    def fields(self) -> frozenset:
        return self.required | self.optional

    def literal_matches(self, keys: Set[str],
                        consts: Dict[str, object], exact: bool) -> bool:
        """Static frame inference over a dict literal: ``keys`` are the
        literal keys, ``consts`` the literal constant values. ``exact``
        requires all required fields present (no dynamic elements)."""
        if not keys <= self.fields:
            return False
        if exact and not self.required <= keys:
            return False
        for k, want in self.when:
            if exact and k not in keys:
                return False
            if want is not None and k in consts and consts[k] != want:
                return False
        return True


def load_wire_schemas(ms: ModuleSource
                      ) -> Tuple[Dict[str, FrameSchema], Dict[str, str],
                                 List[Violation]]:
    """Parse ``register_frame`` declarations out of the wire module.
    Returns (schemas by name, const-name -> frame-name, violations for
    non-literal declarations — those would silently fall out of the
    static pass, so they fail loudly)."""
    schemas: Dict[str, FrameSchema] = {}
    const_map: Dict[str, str] = {}
    bad: List[Violation] = []
    for node in ms.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "register_frame"):
            continue
        const = node.targets[0].id
        call = node.value
        try:
            name = ast.literal_eval(call.args[0])
            kw = {k.arg: ast.literal_eval(k.value) for k in call.keywords}
        except (ValueError, SyntaxError):
            bad.append(Violation(
                ms.path, node.lineno, node.col_offset, "DL009",
                RULES["DL009"][0],
                f"register_frame({const}) uses non-literal arguments: the "
                f"static conformance pass cannot see this frame",
                "<module>"))
            continue
        req, opt = set(), set()
        for fname, _ftype, mode, _since, _doc in kw.get("fields", ()):
            (req if mode == "required" else opt).add(fname)
        schemas[name] = FrameSchema(
            name=name, version=int(kw.get("version", 1)),
            required=frozenset(req), optional=frozenset(opt),
            when=tuple(sorted((kw.get("when") or {}).items())),
            line=node.lineno, const=const)
        const_map[const] = name
    return schemas, const_map, bad


# ---------------------------------------------------------- per-module scan

class _WireScan(ast.NodeVisitor):
    """Collect wire anchors, dict-literal key flows and codec encode call
    sites for one module. Flow-insensitive within a function scope: keys
    from the dict literal, later ``var[k] = ...`` stores and
    ``var.update(k=...)`` calls all merge into the variable's key set."""

    def __init__(self, ms: ModuleSource, schemas: Dict[str, FrameSchema],
                 const_map: Dict[str, str]):
        self.ms = ms
        self.schemas = schemas
        self.const_map = const_map
        self.violations: List[Violation] = []
        # (frame, key) reads observed through decode anchors (module-wide)
        self.decode_reads: Set[Tuple[str, str]] = set()
        self.decode_anchored_frames: Set[str] = set()
        self._classes: List[str] = []
        self._funcs: List[str] = []
        # per-function state, reset at function entry
        self._var_keys: Dict[str, Set[str]] = {}
        self._var_consts: Dict[str, Dict[str, object]] = {}
        self._var_dynamic: Dict[str, bool] = {}
        self._encode_anchored: Dict[str, Tuple[str, ...]] = {}
        self._encode_lines: Dict[str, int] = {}
        self._decode_vars: Dict[str, Tuple[str, ...]] = {}
        self._imports: Dict[str, str] = {}
        # module-level frame-tuple aliases: _KV_FRAMES = (wire.A, wire.B)
        self._frame_aliases: Dict[str, Tuple[str, ...]] = {}
        from .callgraph import module_name

        self._modname = module_name(ms.path)
        self._is_pkg = ms.path.endswith("/__init__.py")

    # ------------------------------------------------------------ plumbing

    def _scope(self) -> str:
        parts = self._classes + self._funcs
        return ".".join(parts) if parts else "<module>"

    def _suppressed(self, line: int, code: str) -> bool:
        name = RULES[code][0]
        for probe in (line, line - 1):
            tags = self.ms.suppressed.get(probe)
            if tags and (code in tags or name in tags or "all" in tags):
                return True
        return False

    def _emit(self, node: ast.AST, code: str, detail: str) -> None:
        line = getattr(node, "lineno", 0)
        if self._suppressed(line, code):
            return
        name, summary = RULES[code]
        self.violations.append(Violation(
            self.ms.path, line, getattr(node, "col_offset", 0), code, name,
            f"{summary}: {detail}", self._scope()))

    # ------------------------------------------------------------- imports

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.asname:
                self._imports[alias.asname] = alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            pkg = self._modname.split(".")
            up = len(pkg) - node.level + (1 if self._is_pkg else 0)
            base_parts = pkg[:max(up, 0)] + \
                ([node.module] if node.module else [])
            base = ".".join(p for p in base_parts if p)
        for alias in node.names:
            if alias.name != "*":
                self._imports[alias.asname or alias.name] = \
                    f"{base}.{alias.name}" if base else alias.name

    # ------------------------------------------------------------- scoping

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    def _visit_func(self, node) -> None:
        saved = (self._var_keys, self._var_consts, self._var_dynamic,
                 self._encode_anchored, self._encode_lines,
                 self._decode_vars)
        self._var_keys, self._var_consts = {}, {}
        self._var_dynamic, self._encode_anchored = {}, {}
        self._encode_lines, self._decode_vars = {}, {}
        self._funcs.append(node.name)
        self.generic_visit(node)
        self._check_encode_vars()
        self._funcs.pop()
        (self._var_keys, self._var_consts, self._var_dynamic,
         self._encode_anchored, self._encode_lines,
         self._decode_vars) = saved

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # --------------------------------------------------------- anchor utils

    def _frame_names(self, node: ast.AST) -> Optional[Tuple[str, ...]]:
        """Resolve a frame-reference expression: ``wire.CONST``, a bare
        imported CONST, a string literal, a tuple of those, a
        module-level tuple alias (``_KV_FRAMES``) or a ``+`` of tuples.
        ``None`` when it is not a wire-frame reference at all."""
        if isinstance(node, ast.Tuple):
            out: List[str] = []
            for el in node.elts:
                got = self._frame_names(el)
                if got is None:
                    return None
                out.extend(got)
            return tuple(out)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = self._frame_names(node.left)
            right = self._frame_names(node.right)
            return left + right if left and right else None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return (node.value,) if node.value in self.schemas else None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
            if name in self._frame_aliases:
                return self._frame_aliases[name]
        else:
            return None
        frame = self.const_map.get(name)
        return (frame,) if frame else None

    def _anchor_kind(self, call: ast.Call) -> Optional[str]:
        """'checked' / 'decoded' when the call is a wire anchor."""
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name not in (_ANCHOR_ENCODE, _ANCHOR_DECODE) or not call.args:
            return None
        if self._frame_names(call.args[0]) is None:
            return None
        return name

    @staticmethod
    def _dict_literal_keys(node: ast.Dict
                           ) -> Tuple[Set[str], Dict[str, object], bool]:
        keys: Set[str] = set()
        consts: Dict[str, object] = {}
        dynamic = False
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
                if isinstance(v, ast.Constant):
                    consts[k.value] = v.value
            else:
                dynamic = True  # **splat or computed key
        return keys, consts, dynamic

    def _check_keys(self, frames: Tuple[str, ...], keys: Set[str],
                    node: ast.AST, side: str) -> None:
        allowed = frozenset().union(
            *(self.schemas[f].fields for f in frames))
        for key in sorted(keys - allowed):
            self._emit(node, "DL009",
                       f"{side} key `{key}` is not declared on frame "
                       f"{'/'.join(frames)}")

    # ----------------------------------------------------------- statements

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        targets = [t for t in node.targets if isinstance(t, ast.Name)]
        if not self._funcs and targets and isinstance(
                value, (ast.Tuple, ast.BinOp, ast.Attribute, ast.Name)):
            frames = self._frame_names(value)
            if frames:
                for t in targets:
                    self._frame_aliases[t.id] = frames
        if isinstance(value, ast.Call):
            kind = self._anchor_kind(value)
            if kind is not None and len(value.args) >= 2:
                frames = self._frame_names(value.args[0])
                if kind == _ANCHOR_DECODE:
                    self.decode_anchored_frames.update(frames)
                    for t in targets:
                        self._decode_vars[t.id] = frames
                    self.generic_visit(value)
                    return
                # encode anchor: keys flow from the literal or the source
                # var into the result var(s)
                hdr = value.args[1]
                keys: Set[str] = set()
                consts: Dict[str, object] = {}
                if isinstance(hdr, ast.Dict):
                    keys, consts, _dyn = self._dict_literal_keys(hdr)
                elif isinstance(hdr, ast.Name):
                    keys = set(self._var_keys.get(hdr.id, set()))
                    consts = dict(self._var_consts.get(hdr.id, {}))
                for t in targets:
                    self._encode_anchored[t.id] = frames
                    self._encode_lines[t.id] = node.lineno
                    self._var_keys.setdefault(t.id, set()).update(keys)
                    self._var_consts.setdefault(t.id, {}).update(consts)
                self.generic_visit(value)
                return
        if isinstance(value, ast.Dict) and targets:
            keys, consts, dyn = self._dict_literal_keys(value)
            for t in targets:
                self._var_keys.setdefault(t.id, set()).update(keys)
                self._var_consts.setdefault(t.id, {}).update(consts)
                if dyn:
                    self._var_dynamic[t.id] = True
        self.generic_visit(node)

    # ---------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        kind = self._anchor_kind(node)
        if kind is not None and len(node.args) >= 2:
            frames = self._frame_names(node.args[0])
            hdr = node.args[1]
            if kind == _ANCHOR_DECODE:
                self.decode_anchored_frames.update(frames)
                if isinstance(hdr, ast.Dict):
                    keys, _c, _d = self._dict_literal_keys(hdr)
                    self._check_keys(frames, keys, node, "decoded")
            else:
                if isinstance(hdr, ast.Dict):
                    keys, _c, _d = self._dict_literal_keys(hdr)
                    self._check_keys(frames, keys, node, "encoded")
                elif isinstance(hdr, ast.Name):
                    self._encode_anchored[hdr.id] = frames
        # var.update(key=...) key flow + decode-read via .get
        attr = call_attr(node)
        if attr == "update" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name):
            var = node.func.value.id
            kw_keys = {k.arg for k in node.keywords if k.arg}
            if var in self._var_keys or var in self._encode_anchored:
                self._var_keys.setdefault(var, set()).update(kw_keys)
            if var in self._decode_vars:
                self._note_reads(var, kw_keys, node)
        if attr in ("get", "pop", "setdefault") \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            var = node.func.value.id
            if var in self._decode_vars:
                self._note_reads(var, {node.args[0].value}, node)
        self._check_codec_site(node)
        self.generic_visit(node)

    def _note_reads(self, var: str, keys: Set[str], node: ast.AST) -> None:
        frames = self._decode_vars[var]
        self._check_keys(frames, keys, node, "decoded")
        for key in keys:
            for f in frames:
                if key in self.schemas[f].fields:
                    self.decode_reads.add((f, key))

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.value, ast.Name) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            var, key = node.value.id, node.slice.value
            if isinstance(node.ctx, ast.Store):
                if var in self._var_keys or var in self._encode_anchored:
                    self._var_keys.setdefault(var, set()).add(key)
            elif var in self._decode_vars:
                self._note_reads(var, {key}, node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # `"key" in var` on a decode-anchored var counts as a read
        if len(node.ops) == 1 and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str) \
                and isinstance(node.comparators[0], ast.Name) \
                and node.comparators[0].id in self._decode_vars:
            self._note_reads(node.comparators[0].id,
                             {node.left.value}, node)
        self.generic_visit(node)

    # -------------------------------------------------- encode-var checking

    def _check_encode_vars(self) -> None:
        """At function exit, validate accumulated keys of every var that
        passed through a wire.checked encode anchor."""
        for var, frames in self._encode_anchored.items():
            keys = self._var_keys.get(var)
            if keys:
                # report at... we lack a node; synthesize at function level
                allowed = frozenset().union(
                    *(self.schemas[f].fields for f in frames))
                extra = sorted(keys - allowed)
                if extra:
                    # anchor-line unknown: attribute to the first line the
                    # scan saw for this function (best effort, scope-keyed)
                    v = Violation(
                        self.ms.path, self._encode_lines.get(var, 0), 0,
                        "DL009", RULES["DL009"][0],
                        f"{RULES['DL009'][1]}: encoded key(s) {extra} not "
                        f"declared on frame {'/'.join(frames)}",
                        self._scope())
                    if not self._suppressed(v.line, "DL009"):
                        self.violations.append(v)

    # ------------------------------------------------------- DL010 (codec)

    def _codec_fn(self, call: ast.Call) -> Optional[str]:
        """'encode' / 'encode_parts' when the call resolves to the codec
        module's encoders (via alias or module attribute)."""
        d = dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        tail = parts[-1]
        if tail not in ("encode", "encode_parts"):
            return None
        if len(parts) == 1:
            target = self._imports.get(tail, "")
            return tail if target.endswith(f"codec.{tail}") else None
        base = self._imports.get(parts[0], parts[0])
        full = ".".join([base] + parts[1:-1])
        return tail if full.endswith("codec") else None

    def _header_expr(self, call: ast.Call, which: str) -> Optional[ast.AST]:
        if which == "encode_parts":
            return call.args[0] if call.args else None
        # encode(TwoPartMessage(header=..., ...)) / encode(msg)
        if not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Call) and (
                (isinstance(arg.func, ast.Name)
                 and arg.func.id == "TwoPartMessage")
                or (isinstance(arg.func, ast.Attribute)
                    and arg.func.attr == "TwoPartMessage")):
            for kw in arg.keywords:
                if kw.arg == "header":
                    return kw.value
            return arg.args[0] if arg.args else None
        return arg

    def _check_codec_site(self, node: ast.Call) -> None:
        which = self._codec_fn(node)
        if which is None:
            return
        norm = self.ms.path
        if norm.endswith(("runtime/codec.py", "runtime/wire.py")):
            return  # the codec/registry internals themselves
        hdr = self._header_expr(node, which)
        if hdr is None:
            return
        if isinstance(hdr, ast.Call) and self._anchor_kind(hdr) is not None:
            return  # wire.checked(...) inline — anchored
        keys: Optional[Set[str]] = None
        consts: Dict[str, object] = {}
        exact = False
        if isinstance(hdr, ast.Name):
            if hdr.id in self._encode_anchored:
                return  # var passed through wire.checked earlier
            if hdr.id in self._var_keys:
                keys = self._var_keys[hdr.id]
                consts = self._var_consts.get(hdr.id, {})
                exact = not self._var_dynamic.get(hdr.id, False)
        elif isinstance(hdr, ast.Dict):
            keys, consts, dyn = self._dict_literal_keys(hdr)
            exact = not dyn
        if keys is None:
            return  # opaque header (built elsewhere): never guess
        if not any(s.literal_matches(keys, consts, exact)
                   for s in self.schemas.values()):
            self._emit(node, "DL010",
                       f"header keys {sorted(keys)} match no registered "
                       f"frame — declare it in runtime/wire.py and anchor "
                       f"with wire.checked(...)")


# ---------------------------------------------------------------- DL008 pass

def check_transitive_blocking(graph: CallGraph,
                              depth: int = DEFAULT_DL008_DEPTH
                              ) -> List[Violation]:
    reach = graph.blocking_reachability(depth)
    out: List[Violation] = []
    seen: Set[Tuple[str, str]] = set()
    name, summary = RULES["DL008"]
    for fi in graph.functions.values():
        if not fi.is_async:
            continue
        mod = graph.modules[fi.module]
        for cs in fi.calls:
            bp = reach.get(cs.target) if cs.target else None
            if bp is None or bp.depth + 1 > depth:
                continue
            if (fi.key, cs.target) in seen:
                continue
            seen.add((fi.key, cs.target))
            suppressed = False
            for probe in (cs.line, cs.line - 1):
                tags = mod.suppressed.get(probe)
                if tags and ({"DL008", name, "all"} & tags):
                    suppressed = True
            if suppressed:
                continue
            chain = " -> ".join(
                k.split(":", 1)[1] for k in [cs.target] + bp.chain[1:])
            out.append(Violation(
                fi.path, cs.line, cs.col, "DL008", name,
                f"{summary}: `{cs.raw}` reaches blocking `{bp.what}` via "
                f"{chain} ({bp.sink_path}:{bp.sink_line})",
                fi.qualname))
    return out


# -------------------------------------------------------------- DL009 global

def _check_required_never_read(
        schemas: Dict[str, FrameSchema], wire_path: str,
        decode_reads: Set[Tuple[str, str]],
        anchored: Set[str],
        wire_suppressed: Dict[int, Set[str]]) -> List[Violation]:
    """A required field no decode anchor anywhere reads is dead weight on
    every frame (or a decoder forgot it) — flagged at its registration."""
    out: List[Violation] = []
    name, summary = RULES["DL009"]
    for schema in schemas.values():
        if schema.name not in anchored:
            continue  # no decoder in the scanned tree: cannot judge
        unread = sorted(k for k in schema.required
                        if (schema.name, k) not in decode_reads)
        for key in unread:
            suppressed = any(
                tags and ({"DL009", name, "all"} & tags)
                for tags in (wire_suppressed.get(schema.line),
                             wire_suppressed.get(schema.line - 1)))
            if suppressed:
                continue
            out.append(Violation(
                wire_path, schema.line, 0, "DL009", name,
                f"{summary}: required field `{key}` of frame "
                f"`{schema.name}` is never read by any decode anchor — "
                f"demote it to optional or fix the decoder",
                schema.name))
    return out


# ------------------------------------------------------------------- driver

def analyze_project(sources: Sequence[ModuleSource],
                    schemas: Optional[Dict[str, FrameSchema]] = None,
                    const_map: Optional[Dict[str, str]] = None,
                    dl008_depth: int = DEFAULT_DL008_DEPTH,
                    graph: Optional[CallGraph] = None
                    ) -> List[Violation]:
    """Run the whole-program passes over already-loaded modules. The wire
    registry defaults to the scanned module whose path is
    ``dynamo_tpu/runtime/wire.py``; pass ``schemas``/``const_map``
    explicitly for fixture trees, ``graph`` to reuse an already-built
    call graph (the --all driver shares one with dynarace)."""
    out: List[Violation] = []
    wire_ms = next((m for m in sources if m.path == WIRE_MODULE_REL), None)
    if schemas is None and wire_ms is not None:
        schemas, const_map, bad = load_wire_schemas(wire_ms)
        out.extend(bad)
    if graph is None:
        graph = CallGraph.build(sources)
    out.extend(check_transitive_blocking(graph, dl008_depth))
    if schemas:
        decode_reads: Set[Tuple[str, str]] = set()
        anchored: Set[str] = set()
        for ms in sources:
            scan = _WireScan(ms, schemas, const_map or {
                s.const: s.name for s in schemas.values()})
            scan.visit(ms.tree)
            out.extend(scan.violations)
            decode_reads |= scan.decode_reads
            anchored |= scan.decode_anchored_frames
        wire_path = wire_ms.path if wire_ms is not None else WIRE_MODULE_REL
        wire_suppr = wire_ms.suppressed if wire_ms is not None else {}
        out.extend(_check_required_never_read(
            schemas, wire_path, decode_reads, anchored, wire_suppr))
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out


def analyze_tree(paths: Sequence[str], root: Optional[str] = None,
                 dl008_depth: int = DEFAULT_DL008_DEPTH,
                 timings: Optional[dict] = None,
                 proto_report: Optional[dict] = None,
                 per_file_paths: Optional[Sequence[str]] = None
                 ) -> List[Violation]:
    """Per-file rules + whole-program dynaflow rules + the dynarace
    concurrency passes + the dynajit / dynaproto / dynahot / dynaform
    passes (and the protocol model checker) over one tree; the shared
    parse cache means each file is read and parsed exactly once per run.
    Pass ``timings={}`` to receive per-pass wall seconds (``per_file``/
    ``dynaflow``/``dynarace``/``dynajit``/``dynaproto``/``modelcheck``/
    ``dynahot``/``dynaform``) and ``proto_report={}`` for the
    per-machine model-checker stats (``--json``'s ``protocols`` block).

    ``per_file_paths`` (the ``--changed`` incremental mode) scopes the
    PER-FILE rules to those files only; the whole-program passes always
    see the full tree — a callgraph built from a diff would miss every
    cross-file edge that makes them sound."""
    import time as _time

    from .analyzer import analyze_module

    t0 = _time.perf_counter()
    sources = load_sources(paths, root=root)
    per_file_abs = (None if per_file_paths is None else
                    {os.path.abspath(p) for p in per_file_paths})
    out: List[Violation] = []
    for ms in sources:
        if per_file_abs is None or ms.abspath in per_file_abs:
            out.extend(analyze_module(ms))
    # unparseable files: analyze_paths-style DL000s come from the per-file
    # entry; load_sources skipped them, so re-walk for syntax errors
    import ast as _ast

    from .analyzer import iter_py_files

    loaded = {m.abspath for m in sources}
    root_abs = os.path.abspath(root or os.getcwd())
    for f in iter_py_files(paths):
        ab = os.path.abspath(f)
        if ab in loaded:
            continue
        if per_file_abs is not None and ab not in per_file_abs:
            continue
        rel = os.path.relpath(ab, root_abs) \
            if ab.startswith(root_abs + os.sep) else f
        try:
            with open(ab, encoding="utf-8") as fh:
                _ast.parse(fh.read(), filename=rel)
        except SyntaxError as e:
            out.append(Violation(rel.replace(os.sep, "/"), e.lineno or 0, 0,
                                 "DL000", "syntax-error", str(e),
                                 "<module>"))
    t1 = _time.perf_counter()
    graph = CallGraph.build(sources)
    out.extend(analyze_project(sources, dl008_depth=dl008_depth,
                               graph=graph))
    t2 = _time.perf_counter()
    from .dynarace import analyze_races

    race_out: dict = {}
    out.extend(analyze_races(sources, graph=graph, model_out=race_out))
    t3 = _time.perf_counter()
    from .dynajit import analyze_jit

    out.extend(analyze_jit(sources, graph=graph))
    t4 = _time.perf_counter()
    from .dynaproto import analyze_protocols

    out.extend(analyze_protocols(sources, graph=graph,
                                 race_model=race_out.get("model")))
    t5 = _time.perf_counter()
    from .modelcheck import check_protocol_models

    out.extend(check_protocol_models(sources, report_out=proto_report))
    t6 = _time.perf_counter()
    from .dynahot import analyze_hot

    out.extend(analyze_hot(sources, graph=graph))
    t7 = _time.perf_counter()
    from .dynaform import analyze_form

    out.extend(analyze_form(sources, graph=graph))
    t8 = _time.perf_counter()
    if timings is not None:
        timings["per_file"] = round(t1 - t0, 3)
        timings["dynaflow"] = round(t2 - t1, 3)
        timings["dynarace"] = round(t3 - t2, 3)
        timings["dynajit"] = round(t4 - t3, 3)
        timings["dynaproto"] = round(t5 - t4, 3)
        timings["modelcheck"] = round(t6 - t5, 3)
        timings["dynahot"] = round(t7 - t6, 3)
        timings["dynaform"] = round(t8 - t7, 3)
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out
