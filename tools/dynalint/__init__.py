"""dynalint: project-native async/JAX static analysis for dynamo-tpu.

The Rust reference gets its concurrency safety from the borrow checker;
this Python/JAX port gets it from here. The per-file AST rules
(DL001-DL007) catch the hazard classes that bite async serving stacks at
3am: blocking calls on the event loop, background tasks whose exceptions
vanish, silently-spinning error loops, blocking work under locks, host
syncs in engine hot paths, undocumented env knobs and leaked trace
spans. The **dynaflow** whole-program layer (callgraph.py + dynaflow.py)
adds what no single file can show: blocking calls reachable from async
defs through chains of sync helpers (DL008), and conformance of every
encoded/decoded wire frame against the declared schema registry in
``dynamo_tpu/runtime/wire.py`` (DL009/DL010). The **dynarace** layer
(dynarace.py) infers concurrency roots and shared state over the same
call graph and enforces await-atomicity (DL012), the ``# guarded-by:``
lock/loop discipline (DL013), lock-order consistency (DL014), and the
interprocedural extension of the DL005 hot-path host-sync rule. The
**dynajit** layer (dynajit.py) guards the engine's zero-compile
serving invariant with a device-residency + shape-provenance dataflow
pass: recompile hazards at jitted call sites plus warmup coverage
(DL015), donation discipline (DL016), and implicit host transfers
(DL017) — the static twin of the runtime compile fence in
``dynamo_tpu/engine/jit_fence.py``. The **dynahot** layer (dynahot.py)
computes hot regions by callgraph reachability from the declared
``HOT_ROOTS`` registry (scheduler-iteration + per-token roots, with
per-frame accumulated loop depth) and enforces no loop-invariant work
re-done per iteration (DL022), no eager formatting into log/trace
calls on hot frames (DL023), and no unbounded ``self.<attr>``
collection growth on the request path (DL024, justified exceptions via
``# bounded-by: <reason>``). The **dynaform** layer (dynaform.py) types
every expression on a dtype x provenance lattice (bf16/fp32/int8/weak
scalars x committed/uncommitted/literal/bucketed) and enforces no
silent weak-type widening of bf16/int8 device values in hot regions
(DL025, justified exceptions via ``# promote-ok: <reason>``),
warmup/serving jit call-form equivalence — arity, operand dtype and
committedness, explicit-kwarg sets, static kwarg value sets,
list-convert forms — so every serving-path call form is pre-compiled
(DL026, subsuming dynajit's per-entry warmup-coverage check), and the
int8 host-tier quantize/dequantize pairing contract (DL027).

Usage:
    python -m tools.dynalint --all          # every pass, one parse
    python -m tools.dynalint --changed      # pre-commit: per-file rules
                                            # on the git diff only
    python -m tools.dynalint [--baseline FILE] [--json] paths...
    python -m tools.dynalint --callgraph-dot graph.dot
    python -m tools.dynalint --wire-schemas docs/wire_schemas.md

Suppression: append ``# dynalint: disable=<rule-name>[,<rule-name>...]``
to the offending line (or the line directly above it). Grandfathered
violations live in ``tools/dynalint/baseline.txt`` — the gate is
ratchet-only: new violations fail, baselined ones pass, stale baseline
entries warn.
"""

from .analyzer import (RULES, ModuleSource, Violation, analyze_paths,
                       analyze_source, iter_py_files, load_source,
                       load_sources, parse_module)
from .baseline import apply_baseline, format_entry, load_baseline
from .callgraph import DEFAULT_DL008_DEPTH, CallGraph, module_name
from .dynaflow import (FrameSchema, analyze_project, analyze_tree,
                       load_wire_schemas)
from .dynaform import FormSite, FormVal, analyze_form, check_form_drift
from .dynahot import (HOT_FRAME_RE, HOT_ROOTS, HotFrame, analyze_hot,
                      hot_regions)
from .dynajit import JitInfo, analyze_jit, collect_jits
from .dynaproto import (ProtoSchema, analyze_protocols, collect_anchors,
                        load_protocols, protocols_to_dot)
from .dynarace import (RaceModel, analyze_races, build_race_model,
                       check_transitive_host_sync, scan_modules)
from .modelcheck import check_models, check_protocol_models, explore

__all__ = [
    "RULES", "CallGraph", "DEFAULT_DL008_DEPTH", "FormSite", "FormVal",
    "FrameSchema", "HOT_FRAME_RE", "HOT_ROOTS", "HotFrame", "JitInfo",
    "ModuleSource", "ProtoSchema", "RaceModel", "Violation",
    "analyze_form", "analyze_hot", "analyze_jit", "analyze_paths",
    "analyze_project", "analyze_protocols", "analyze_races",
    "analyze_source", "analyze_tree", "apply_baseline",
    "build_race_model", "check_form_drift", "check_models",
    "check_protocol_models", "check_transitive_host_sync",
    "collect_anchors", "collect_jits", "explore", "format_entry",
    "hot_regions", "iter_py_files", "load_protocols", "load_source",
    "load_sources", "load_wire_schemas", "load_baseline", "module_name",
    "parse_module", "protocols_to_dot", "scan_modules",
]
