"""dynalint: project-native async/JAX static analysis for dynamo-tpu.

The Rust reference gets its concurrency safety from the borrow checker;
this Python/JAX port gets it from here. Six AST rules catch the hazard
classes that bite async serving stacks at 3am: blocking calls on the
event loop, background tasks whose exceptions vanish, silently-spinning
error loops, blocking work under locks, host syncs in engine hot paths,
and undocumented env knobs.

Usage:
    python -m tools.dynalint [--baseline FILE] [--json] paths...

Suppression: append ``# dynalint: disable=<rule-name>[,<rule-name>...]``
to the offending line (or the line directly above it). Grandfathered
violations live in ``tools/dynalint/baseline.txt`` — the gate is
ratchet-only: new violations fail, baselined ones pass, stale baseline
entries warn.
"""

from .analyzer import (RULES, Violation, analyze_paths, analyze_source,
                       iter_py_files)
from .baseline import apply_baseline, format_entry, load_baseline

__all__ = [
    "RULES", "Violation", "analyze_paths", "analyze_source",
    "apply_baseline", "format_entry", "iter_py_files", "load_baseline",
]
