"""dynaform: dtype-provenance & warmup/serving call-form equivalence
(DL025-DL027).

The compile fence has caught the same bug class three times at runtime:
serving-path jitted call forms that ``warmup()`` never exercised
(explicit-vs-defaulted kwargs, committed-vs-uncommitted carries under a
mesh, ``jnp.asarray(<python list>)`` lowering one tiny program per
padded length). Each cost a multi-second first-request compile in
deployment before the fence flagged it. Separately, JAX weak-type
promotion silently widens bf16/int8 device values to fp32 — a 2x-4x
bytes/FLOP hazard on the HBM-bound decode path that no shape-level rule
sees. dynaform moves both bug classes to lint time, on the same shared
parse + call graph as dynaflow/dynajit/dynahot.

The analysis types every expression along two axes:

- **dtype** — ``bf16`` / ``fp32`` / ``fp16`` / ``int8`` / ``int32`` /
  ``weak-i`` / ``weak-f`` (python scalars, which JAX promotes weakly) /
  ``bool`` / ``none`` / ``?``. Knowledge comes only from explicit
  evidence: dtype arguments to constructors, ``.astype``, the typed
  engine pools (``kv_k``/``kv_v``/``params`` are bf16 by config
  default), scale pools (fp32). Unknown matches anything — a
  whole-program lint must never guess.
- **provenance** — ``committed`` (jit-call results, the device pools:
  carries a NamedSharding under a mesh), ``uncommitted`` (host-built
  ``jnp.*``/``np.*`` arrays: a DIFFERENT jit cache entry under a mesh),
  ``literal`` (python scalars), ``bucketed`` (results of the dynajit
  bucket helpers), or ``?``.

Rules (tier-1-enforced with an EMPTY baseline):

- **DL025 silent-dtype-promotion** — inside hot regions (dynahot's
  ``HOT_ROOTS`` reachability) in engine/models code, an arithmetic mix
  whose JAX promotion WIDENS a known-bf16/int8 device value to
  fp32/fp16: ``bf16 (+) fp32`` widens; ``int8 (+) python-float`` widens
  to fp32; ``bf16 (+) python-float`` stays bf16 and is deliberately
  quiet (that is the weak-type fast path). Suppress deliberate
  promotions with ``# promote-ok: <reason>`` — the fp32 is then
  documented as the point (e.g. softmax accumulation).
- **DL026 warmup-form-drift** — for every jitted entry (``@jax.jit``
  defs and the engine's ``self.<x>_fn`` convention) the *call-form key*
  at each serving site is matched against the warmup sites: positional
  arity, per-operand (dtype, committedness, None-vs-array treedef),
  the explicit-kwarg name set, and the statically-enumerable value set
  of scalar kwargs (static argnames key the jit cache per VALUE — a
  serving kwarg value set not covered by warmup is a first-request
  compile). A serving form with no warmup match fires, naming the
  nearest warmup form and the drifted fields. The rule also owns the
  two coarser checks it subsumes: entries dispatched but never warmed
  at all (folded in from DL015, which keeps its shape rules), and
  ``jnp.asarray(<python list>)`` built on the serving path with no
  warmup site of the same dtype list form (each distinct padded length
  lowers its own tiny convert program).
- **DL027 tier-dtype-contract** — the int8 host-tier invariants:
  int8-tier page reads (``host_k``/``host_v`` under a
  ``host_tier_int8`` guard) must flow through ``dequantize_pages``
  before any fp consumer (``_inject_pages``/step fns/arithmetic);
  ``dequantize_pages`` must receive its scale tensor (exactly two
  array args); a tuple-unpacked ``q, s = quantize_pages(...)`` whose
  scale is never used afterwards silently drops the scales; and the
  fp16-fallback branch must never touch the scale pools or dequantize
  (tier mixing).

Suppression: the usual ``# dynalint: disable=<rule>`` on the line or
the line above; DL025 additionally honors ``# promote-ok: <reason>``.
Policy (docs/static_analysis.md): fix form drift by warming the
serving form, not by suppressing — suppression is for forms that are
statically visible but unreachable in deployment.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .analyzer import RULES, ModuleSource, Violation, call_attr, dotted
from .callgraph import CallGraph
from .dynajit import (BUCKET_HELPERS, CONFIG_BASE_RE, DEVICE_MODULE_MARKERS,
                      DEVICE_POOL_ATTRS, ENGINE_MARKER, HOST_POOL_ATTRS,
                      JIT_ATTR_RE, JNP_BASES, NP_BASES, JitInfo, _DUMMY_FI,
                      _jit_decorator_kw, _suppressed, collect_jits)

# ------------------------------------------------------------------- config

# `# promote-ok: <reason>` — a justified deliberate widening
PROMOTE_OK_RE = re.compile(r"#\s*promote-ok:\s*\S")

# dtype-name tails (jnp.int32 / np.float32 / "bfloat16" / bool) -> token
_DTYPE_BY_NAME = {
    "bfloat16": "bf16", "bf16": "bf16",
    "float32": "fp32", "f32": "fp32", "float64": "fp32", "float_": "fp32",
    "float16": "fp16", "f16": "fp16",
    "int8": "int8", "uint8": "int8",
    "int32": "int32", "uint32": "uint32", "int64": "int64",
    "int16": "int32", "int_": "int64",
    "bool_": "bool", "bool": "bool",
}
_FLOATS = frozenset({"bf16", "fp16", "fp32"})
_INTS = frozenset({"int8", "int32", "int64", "uint32"})

# constructors whose result dtype defaults to fp32 when no dtype is given
_FP_DEFAULT_CTORS = frozenset({"zeros", "ones", "empty"})
# jnp/np elementwise ops that promote their operands (DL025 surface)
_PROMOTING_OPS = frozenset({
    "add", "subtract", "multiply", "divide", "true_divide", "power",
    "mod", "remainder", "maximum", "minimum", "where", "clip",
})
_ARITH_BINOPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Pow,
                 ast.Mod, ast.FloorDiv, ast.MatMult)

# the int8 host-tier pair (DL027 anchors)
_QUANT_FNS = frozenset({"quantize_pages", "quantize_pages_np"})
_DEQUANT_FNS = frozenset({"dequantize_pages", "dequantize_pages_np"})
_SCALE_POOL_ATTRS = frozenset({"host_k_s", "host_v_s"})
_PAGE_POOL_ATTRS = frozenset({"host_k", "host_v"})
# fp consumers an un-dequantized int8-tier page must never reach
_FP_SINK_NAMES = frozenset({"_inject_pages", "_inject_staged"})
# the host_tier_int8 guard attribute (EngineConfig flag)
_TIER_FLAG = "host_tier_int8"


def _fs(*vals: str) -> FrozenSet[str]:
    return frozenset(vals)


@dataclass
class FormVal:
    """dtype x provenance x treedef-kind (+ static value tokens) for one
    expression. ``?`` fields match anything in DL026 comparisons."""

    dtype: str = "?"
    prov: str = "?"          # committed | uncommitted | literal | bucketed
    kind: str = "?"          # arr | list | tuple | scalar | none | str
    vals: FrozenSet[str] = frozenset()
    elem: Optional["FormVal"] = None
    int8raw: bool = False    # int8-tier page bytes not yet dequantized


UNKNOWN_FV = FormVal()


def _join_fv(a: FormVal, b: FormVal) -> FormVal:
    return FormVal(
        a.dtype if a.dtype == b.dtype else "?",
        a.prov if a.prov == b.prov else "?",
        a.kind if a.kind == b.kind else "?",
        (a.vals | b.vals) if (a.vals and b.vals) else frozenset(),
        a.elem if b.elem is None else (b.elem if a.elem is None
                                       else _join_fv(a.elem, b.elem)),
        a.int8raw or b.int8raw)


def _dtype_token(node: Optional[ast.AST]) -> str:
    """The dtype a node syntactically names, or ``?``."""
    if node is None:
        return "?"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_BY_NAME.get(node.value, "?")
    d = dotted(node)
    if d is None:
        return "?"
    return _DTYPE_BY_NAME.get(d.rsplit(".", 1)[-1], "?")


def _promote(a: str, b: str) -> str:
    """JAX type-promotion result of mixing dtypes ``a`` and ``b``
    (weak scalars promote weakly: bf16 (+) python-float stays bf16)."""
    if a == b:
        return a
    if "?" in (a, b) or "str" in (a, b) or "none" in (a, b):
        return "?"
    for x, y in ((a, b), (b, a)):
        if x == "bool":
            return y
        if x == "weak-i":
            if y in _INTS or y in _FLOATS or y == "weak-f":
                return y
            return "?"
        if x == "weak-f":
            if y in _FLOATS:
                return y
            if y in _INTS:
                return "fp32"      # int array (+) python float widens
            return "?"
    if a in _FLOATS and b in _FLOATS:
        return "fp32"              # bf16/fp16 mixes resolve to fp32
    if a in _INTS and b in _INTS:
        order = ("int8", "uint32", "int32", "int64")
        return max((a, b), key=order.index) if a in order and b in order \
            else "?"
    if (a in _FLOATS) != (b in _FLOATS):
        return "fp32"              # int array (+) float array
    return "?"


def _dt_compat(a: str, b: str) -> bool:
    """DL026 operand-dtype compatibility: a weak scalar hits the same
    jit cache entry as the array dtype it promotes into."""
    if a == "?" or b == "?" or a == b:
        return True
    weak = {"weak-i": _INTS | {"weak-i"}, "weak-f": _FLOATS | {"weak-f"}}
    if a in weak:
        return b in weak[a]
    if b in weak:
        return a in weak[b]
    return False


# ---------------------------------------------------------- call-form sites

@dataclass
class FormSite:
    """One statically-extracted jitted call form."""

    entry: str               # display name of the jitted entry
    path: str
    line: int
    warm: bool               # inside a top-level warmup() body
    scope: str               # enclosing qualname
    nargs: Optional[int]     # None when *args present (wildcard arity)
    args: Tuple[Tuple[str, str, str], ...]   # (dtype, prov, kind) per pos
    kwnames: Tuple[str, ...]                 # sorted explicit kwarg names
    kwstar: bool             # **kwargs present (wildcard kwarg set)
    kwargs: Dict[str, Tuple[str, str, str, FrozenSet[str]]] = \
        field(default_factory=dict)

    def render(self) -> str:
        parts: List[str] = (["*"] if self.nargs is None else
                            [f"{dt}/{pv}" if kd != "none" else "None"
                             for dt, pv, kd in self.args])
        for k in self.kwnames:
            dt, pv, kd, vals = self.kwargs[k]
            if vals:
                parts.append(f"{k}={{{', '.join(sorted(vals))}}}")
            elif kd == "none":
                parts.append(f"{k}=None")
            else:
                parts.append(f"{k}={dt}/{pv}")
        if self.kwstar:
            parts.append("**")
        return f"{self.entry}({', '.join(parts)})"


@dataclass
class ListySite:
    """One ``jnp.asarray(<python list>)`` device-convert site."""

    path: str
    line: int
    dtype: str
    warm: bool
    scope: str
    text: str


def _form_mismatches(s: FormSite, w: FormSite) -> Optional[List[str]]:
    """Field-level differences between a serving form and one warmup
    form; [] means the warmup form covers it, None means the forms are
    structurally incomparable (different arity/kwarg sets)."""
    if s.nargs is not None and w.nargs is not None and s.nargs != w.nargs:
        return None
    if not s.kwstar and not w.kwstar and s.kwnames != w.kwnames:
        return None
    diffs: List[str] = []
    if s.nargs is not None and w.nargs is not None:
        for i, ((sd, sp, sk), (wd, wp, wk)) in enumerate(
                zip(s.args, w.args)):
            if not _dt_compat(sd, wd):
                diffs.append(f"arg {i} dtype {sd} vs warmed {wd}")
            if {sp, wp} == {"committed", "uncommitted"}:
                diffs.append(f"arg {i} {sp} vs warmed {wp} — different "
                             f"jit cache entries under a mesh")
            if "none" in (sk, wk) and sk != wk and "?" not in (sk, wk):
                diffs.append(f"arg {i} treedef {sk} vs warmed {wk}")
    for k in s.kwnames:
        if k not in w.kwargs:
            continue
        sd, sp, sk, svals = s.kwargs[k]
        wd, wp, wk, wvals = w.kwargs[k]
        if not _dt_compat(sd, wd):
            diffs.append(f"kwarg `{k}` dtype {sd} vs warmed {wd}")
        if {sp, wp} == {"committed", "uncommitted"}:
            diffs.append(f"kwarg `{k}` {sp} vs warmed {wp} — different "
                         f"jit cache entries under a mesh")
        if "none" in (sk, wk) and sk != wk and "?" not in (sk, wk):
            diffs.append(f"kwarg `{k}` treedef {sk} vs warmed {wk}")
        if svals and wvals and not svals <= wvals:
            extra = ", ".join(sorted(svals - wvals))
            diffs.append(f"kwarg `{k}` serving value(s) {{{extra}}} never "
                         f"warmed (warmup covers "
                         f"{{{', '.join(sorted(wvals))}}})")
    return diffs


# ----------------------------------------------------------- the form scan

class _FormScan(ast.NodeVisitor):
    """One device module: dtype/provenance dataflow over every function
    (jitted bodies included — promotion inside device code is the
    hazard), recording jitted call forms and emitting DL025/DL027."""

    def __init__(self, ms: ModuleSource, modname: str, graph: CallGraph,
                 jits: Dict[str, JitInfo], hot_keys: Set[str]):
        self.ms = ms
        self.modname = modname
        self.graph = graph
        self.jits = jits
        self.hot_keys = hot_keys
        # serving/warmup forms are an engine-layer notion, like dynajit
        self.report = ENGINE_MARKER in ms.path.replace("\\", "/")
        self.violations: List[Violation] = []
        self.sites: List[FormSite] = []
        self.listy: List[ListySite] = []
        self._classes: List[str] = []
        self._funcs: List[str] = []
        self._scopes: List[Dict[str, FormVal]] = []
        self._fn_nodes: List[ast.AST] = []
        self._injit: int = 0
        self._tier: List[str] = []    # "int8" / "fp16" branch context
        self._dropped_scales: Dict[str, Tuple[int, ast.AST]] = {}
        self._mod = graph.modules.get(modname)
        self._src_lines = ms.src.splitlines()

    # ------------------------------------------------------------ plumbing

    def _qualname(self) -> str:
        return ".".join(self._classes + self._funcs) or "<module>"

    def _emit(self, node: ast.AST, code: str, detail: str,
              scope: Optional[str] = None) -> None:
        line = getattr(node, "lineno", 0)
        if _suppressed(self.ms, line, code):
            return
        name, summary = RULES[code]
        self.violations.append(Violation(
            self.ms.path, line, getattr(node, "col_offset", 0), code,
            name, f"{summary}: {detail}", scope or self._qualname()))

    def _promote_ok(self, line: int) -> bool:
        for probe in (line, line - 1):
            if 1 <= probe <= len(self._src_lines) and \
                    PROMOTE_OK_RE.search(self._src_lines[probe - 1]):
                return True
        return False

    def _hot(self) -> bool:
        key = f"{self.modname}:{self._qualname()}"
        return key in self.hot_keys or self._injit > 0

    # ------------------------------------------------------------- scoping

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    def _visit_func(self, node) -> None:
        jitted = any(_jit_decorator_kw(d) is not None
                     for d in node.decorator_list)
        scope: Dict[str, FormVal] = {}
        for a in node.args.posonlyargs + node.args.args + [
                node.args.vararg, node.args.kwarg] + node.args.kwonlyargs:
            if a is not None:
                scope[a.arg] = UNKNOWN_FV
        self._funcs.append(node.name)
        self._scopes.append(scope)
        self._fn_nodes.append(node)
        self._injit += 1 if jitted else 0
        saved_scales = self._dropped_scales
        self._dropped_scales = {}
        for stmt in node.body:
            self.visit(stmt)
        for sname in sorted(self._dropped_scales):
            line, at = self._dropped_scales[sname]
            if not self._name_loaded_after(node, sname, line):
                self._emit(at, "DL027",
                           f"scale tensor `{sname}` from quantize_pages "
                           f"is never used — int8 pages without their "
                           f"scales cannot be dequantized; store/ship "
                           f"the (q, s) pair together")
        self._dropped_scales = saved_scales
        self._injit -= 1 if jitted else 0
        self._fn_nodes.pop()
        self._scopes.pop()
        self._funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _name_loaded_after(self, fn_node, name: str, line: int) -> bool:
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Name) and sub.id == name \
                    and isinstance(sub.ctx, ast.Load) \
                    and getattr(sub, "lineno", 0) > line:
                return True
        return False

    def _lookup(self, name: str) -> FormVal:
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return UNKNOWN_FV

    def _bind(self, name: str, fv: FormVal) -> None:
        if self._scopes:
            old = self._scopes[-1].get(name)
            if old is not None and old is not UNKNOWN_FV:
                fv = _join_fv(old, fv)       # flow-insensitive join
            self._scopes[-1][name] = fv

    # -------------------------------------------------------- the evaluator

    def eval(self, node: Optional[ast.AST]) -> FormVal:  # noqa: C901
        if node is None:
            return UNKNOWN_FV
        if isinstance(node, ast.Constant):
            v = node.value
            if v is None:
                return FormVal("none", "literal", "none", _fs("None"))
            if isinstance(v, bool):
                return FormVal("bool", "literal", "scalar", _fs(repr(v)))
            if isinstance(v, int):
                return FormVal("weak-i", "literal", "scalar", _fs(repr(v)))
            if isinstance(v, float):
                return FormVal("weak-f", "literal", "scalar", _fs(repr(v)))
            if isinstance(v, str):
                return FormVal("str", "literal", "str")
            return UNKNOWN_FV
        if isinstance(node, ast.Name):
            return self._lookup(node.id)
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.Tuple):
            elts = [self.eval(e) for e in node.elts]
            elem = elts[0] if elts else None
            for e in elts[1:]:
                elem = _join_fv(elem, e)
            return FormVal("?", "?", "tuple", frozenset(), elem)
        if isinstance(node, (ast.List, ast.Set)):
            elts = [self.eval(e) for e in node.elts]
            elem = elts[0] if elts else None
            for e in elts[1:]:
                elem = _join_fv(elem, e)
            return FormVal(elem.dtype if elem is not None else "?",
                           "literal", "list", frozenset(), elem)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return FormVal(self.eval(node.elt).dtype
                           if isinstance(node.elt, ast.Constant) else "?",
                           "literal", "list")
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return _join_fv(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node)
        if isinstance(node, ast.UnaryOp):
            inner = self.eval(node.operand)
            try:
                v = ast.literal_eval(node)
                return FormVal(inner.dtype, inner.prov, inner.kind,
                               _fs(repr(v)), inner.elem, inner.int8raw)
            except (ValueError, SyntaxError):
                return FormVal(inner.dtype, inner.prov, inner.kind,
                               frozenset(), inner.elem, inner.int8raw)
        if isinstance(node, (ast.BoolOp, ast.Compare)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self.eval(sub)
            return FormVal("bool", "?", "scalar")
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        return UNKNOWN_FV

    def _eval_attr(self, node: ast.Attribute) -> FormVal:
        base = dotted(node.value)
        if base is not None and CONFIG_BASE_RE.match(base):
            return FormVal("?", "literal", "scalar",
                           _fs(f"cfg:{node.attr}"))
        if base == "self":
            if node.attr in DEVICE_POOL_ATTRS:
                return FormVal("bf16", "committed", "arr")
            if node.attr in _SCALE_POOL_ATTRS:
                self._check_scale_read(node)
                return FormVal("fp32", "uncommitted", "arr")
            if node.attr in _PAGE_POOL_ATTRS:
                tier = self._tier[-1] if self._tier else "?"
                return FormVal("int8" if tier == "int8" else "?",
                               "uncommitted", "arr",
                               int8raw=(tier == "int8"))
            if node.attr in HOST_POOL_ATTRS:
                return FormVal("?", "uncommitted", "arr")
        return UNKNOWN_FV

    def _check_scale_read(self, node: ast.AST) -> None:
        if self._tier and self._tier[-1] == "fp16" and self.report:
            self._emit(node, "DL027",
                       "fp16-fallback branch reads an int8 scale pool — "
                       "the two tier formats must never mix on one path")

    def _elem(self, node: ast.AST) -> FormVal:
        """Loop-iteration element FormVal for ``for x in <node>``."""
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            elts = [self.eval(e) for e in node.elts]
            elem = elts[0] if elts else UNKNOWN_FV
            for e in elts[1:]:
                elem = _join_fv(elem, e)
            return elem
        if isinstance(node, ast.IfExp):
            return _join_fv(self._elem(node.body), self._elem(node.orelse))
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            tail = d.rsplit(".", 1)[-1] if d else None
            if tail in ("sorted", "set", "list", "tuple", "reversed") \
                    and node.args:
                return self._elem(node.args[0])
            if tail == "range":
                return FormVal("weak-i", "literal", "scalar")
        if isinstance(node, ast.Name):
            fv = self._lookup(node.id)
            return fv.elem or UNKNOWN_FV
        fv = self.eval(node)
        return fv.elem or UNKNOWN_FV

    def _eval_binop(self, node: ast.BinOp) -> FormVal:
        left, right = self.eval(node.left), self.eval(node.right)
        if isinstance(node.op, (ast.Mult, ast.Add)) and \
                "list" in (left.kind, right.kind):
            # [0] * n — python list repetition/concat stays a list
            listy = left if left.kind == "list" else right
            return FormVal(listy.dtype, "literal", "list", frozenset(),
                           listy.elem)
        if isinstance(node.op, _ARITH_BINOPS):
            self._check_promotion(node, left, right)
        res = _promote(left.dtype, right.dtype)
        provs = (left.prov, right.prov)
        prov = ("committed" if "committed" in provs
                else "uncommitted" if "uncommitted" in provs
                else left.prov if left.prov == right.prov else "?")
        kind = "arr" if "arr" in (left.kind, right.kind) else (
            left.kind if left.kind == right.kind else "?")
        vals: FrozenSet[str] = frozenset()
        try:
            vals = _fs(repr(ast.literal_eval(node)))
        except (ValueError, SyntaxError, TypeError):
            pass
        return FormVal(res, prov, kind, vals,
                       int8raw=left.int8raw or right.int8raw)

    def _check_promotion(self, node: ast.AST, left: FormVal,
                         right: FormVal) -> None:
        """DL025: fire when a known-bf16/int8 device value is widened to
        fp32/fp16 by the other operand's dtype."""
        if not self._hot() or not self.report:
            return
        line = getattr(node, "lineno", 0)
        for dev, other in ((left, right), (right, left)):
            if dev.dtype not in ("bf16", "int8"):
                continue
            if dev.prov not in ("committed", "uncommitted"):
                continue
            res = _promote(dev.dtype, other.dtype)
            if res in ("?", dev.dtype) or res not in _FLOATS:
                continue
            if self._promote_ok(line):
                return
            src = ast.unparse(node)[:72]
            self._emit(node, "DL025",
                       f"`{src}` promotes a {dev.dtype} device value to "
                       f"{res} ({dev.dtype} (+) {other.dtype}) on a hot "
                       f"path — {2 if dev.dtype == 'bf16' else 4}x the "
                       f"bytes/FLOPs; cast explicitly or justify with "
                       f"`# promote-ok: <reason>`")
            if dev.int8raw:
                self._emit(node, "DL027",
                           "int8-tier page bytes used in fp arithmetic "
                           "without dequantize_pages — the values are "
                           "quantized codes, not activations")
            return

    def _eval_subscript(self, node: ast.Subscript) -> FormVal:
        value = self.eval(node.value)
        idx = node.slice
        parts = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
        for p in parts:
            if not isinstance(p, ast.Slice):
                self.eval(p)
        if value.kind == "arr":
            # a view/gather of an array keeps its dtype & provenance
            return FormVal(value.dtype, value.prov, "arr",
                           int8raw=value.int8raw)
        if value.kind in ("list", "tuple") and value.elem is not None \
                and not any(isinstance(p, ast.Slice) for p in parts):
            return value.elem
        return UNKNOWN_FV

    # ---------------------------------------------------------------- calls

    def _jit_callee(self, node: ast.Call) -> Tuple[Optional[str],
                                                   Optional[JitInfo]]:
        d = dotted(node.func)
        if d is None:
            return None, None
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2 \
                and JIT_ATTR_RE.search(parts[1]):
            return parts[1], None          # step-fn convention
        if self._mod is not None:
            first = self._qualname().split(".")[0]
            cls_name = first if first in self._mod.classes else None
            fi = self._mod.functions.get(self._qualname())
            fi_key = self.graph._resolve(
                self._mod, d, cls_name, fi if fi is not None else _DUMMY_FI)
            if fi_key is not None and fi_key in self.jits:
                return d.rsplit(".", 1)[-1], self.jits[fi_key]
        return None, None

    def _eval_call(self, node: ast.Call) -> FormVal:  # noqa: C901
        d = dotted(node.func)
        tail = d.rsplit(".", 1)[-1] if d else call_attr(node)
        base = d.rsplit(".", 1)[0] if d and "." in d else None

        if tail in BUCKET_HELPERS:
            args = [self.eval(a) for a in node.args]
            if tail == "_pad_pow2":
                # pads a python list: result is a list of the input's
                # element dtype (the serving drains' asarray operand)
                elem = (args[0].elem or UNKNOWN_FV) if args else UNKNOWN_FV
                return FormVal(elem.dtype if elem.dtype != "?"
                               else "weak-i", "bucketed", "list",
                               frozenset(), elem)
            return FormVal("weak-i", "bucketed", "scalar")

        if base in NP_BASES or base in JNP_BASES:
            return self._eval_np_call(node, tail, base in JNP_BASES)

        if tail in _DEQUANT_FNS:
            args = [self.eval(a) for a in node.args]
            for k in node.keywords:
                self.eval(k.value)
            if self.report and len(node.args) < 2 and not any(
                    isinstance(a, ast.Starred) for a in node.args):
                self._emit(node, "DL027",
                           f"`{tail}` called without its scale tensor — "
                           f"int8 pages dequantize as (q, s) pairs")
            if self.report and self._tier and self._tier[-1] == "fp16":
                self._emit(node, "DL027",
                           f"`{tail}` on the fp16-fallback branch — the "
                           f"two tier formats must never mix on one path")
            return FormVal("fp32", "committed" if tail == "dequantize_pages"
                           else "uncommitted", "arr")
        if tail in _QUANT_FNS:
            for a in node.args:
                self.eval(a)
            return FormVal("int8", "committed" if tail == "quantize_pages"
                           else "uncommitted", "tuple")

        jit_name, info = self._jit_callee(node)
        if jit_name is not None:
            return self._note_jit_call(node, jit_name, info)

        if tail == "len":
            for a in node.args:
                self.eval(a)
            return FormVal("weak-i", "literal", "scalar")
        if tail in ("min", "max", "sum", "abs", "round"):
            for a in node.args:
                self.eval(a)
            return FormVal("weak-i", "?", "scalar")
        if tail == "append" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) and node.args:
            # list.append widens the stored element join
            nm = node.func.value.id
            cur = self._lookup(nm)
            item = self.eval(node.args[0])
            if cur.kind == "list":
                self._bind(nm, FormVal(
                    "?", cur.prov, "list", frozenset(),
                    item if cur.elem is None else _join_fv(cur.elem, item)))
            return UNKNOWN_FV
        if tail == "astype" and isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
            dt = _dtype_token(node.args[0]) if node.args else "?"
            return FormVal(dt, recv.prov, "arr", int8raw=recv.int8raw)

        for a in node.args:
            self.eval(a)
        for k in node.keywords:
            self.eval(k.value)
        if isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
            if recv.prov == "committed" and recv.kind == "arr":
                return FormVal("?", "committed", "arr")
        return UNKNOWN_FV

    def _eval_np_call(self, node: ast.Call, tail: Optional[str],
                      is_jnp: bool) -> FormVal:
        prov = "uncommitted"
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if tail in _FP_DEFAULT_CTORS or tail == "full" or tail == "arange":
            dt_node = kw.get("dtype")
            if dt_node is None:
                pos = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                       "arange": 3}.get(tail or "", 99)
                if len(node.args) > pos:
                    dt_node = node.args[pos]
            dt = _dtype_token(dt_node)
            if dt == "?" and dt_node is None:
                if tail == "full" and len(node.args) > 1:
                    fill = self.eval(node.args[1])
                    dt = {"weak-i": "int32", "weak-f": "fp32",
                          "bool": "bool"}.get(fill.dtype, "?")
                elif tail == "arange":
                    dt = "int32"
                else:
                    dt = "fp32"        # zeros/ones/empty default
            for a in node.args:
                self.eval(a)
            return FormVal(dt, prov, "arr")
        if tail in ("asarray", "array"):
            src = self.eval(node.args[0]) if node.args else UNKNOWN_FV
            dt_node = kw.get("dtype") or (node.args[1]
                                          if len(node.args) > 1 else None)
            dt = _dtype_token(dt_node)
            if dt == "?" and dt_node is None:
                dt = {"weak-i": "int32", "weak-f": "fp32"}.get(
                    src.dtype, src.dtype)
            if is_jnp and src.kind == "list":
                self._note_listy(node, dt)
            return FormVal(dt, prov, "arr", frozenset(),
                           int8raw=src.int8raw)
        if tail in _PROMOTING_OPS:
            args = [self.eval(a) for a in node.args]
            rel = args[1:] if tail in ("where", "clip") else args
            for i in range(len(rel)):
                for j in range(i + 1, len(rel)):
                    self._check_promotion(node, rel[i], rel[j])
            dt = "?"
            if rel:
                dt = rel[0].dtype
                for r in rel[1:]:
                    dt = _promote(dt, r.dtype)
            return FormVal(dt, prov if not is_jnp else (
                "committed" if any(a.prov == "committed" for a in args)
                else prov), "arr")
        for a in node.args:
            self.eval(a)
        for k in node.keywords:
            self.eval(k.value)
        return FormVal("?", prov, "arr")

    def _note_listy(self, node: ast.Call, dt: str) -> None:
        if not self.report or self._injit > 0 or not self._funcs:
            return
        self.listy.append(ListySite(
            self.ms.path, getattr(node, "lineno", 0), dt,
            self._funcs[0] == "warmup", self._qualname(),
            ast.unparse(node)[:64]))

    def _note_jit_call(self, node: ast.Call, name: str,
                       info: Optional[JitInfo]) -> FormVal:
        starred = any(isinstance(a, ast.Starred) for a in node.args)
        kwstar = any(k.arg is None for k in node.keywords)
        arg_keys: List[Tuple[str, str, str]] = []
        for a in node.args:
            fv = self.eval(a)
            if not starred:
                arg_keys.append((fv.dtype, fv.prov, fv.kind))
            if self.report and fv.int8raw:
                self._emit(node, "DL027",
                           f"int8-tier page bytes flow into jitted "
                           f"`{name}` without dequantize_pages — the "
                           f"values are quantized codes, not KV rows")
        kwargs: Dict[str, Tuple[str, str, str, FrozenSet[str]]] = {}
        for k in node.keywords:
            fv = self.eval(k.value)
            if k.arg is not None:
                kwargs[k.arg] = (fv.dtype, fv.prov, fv.kind, fv.vals)
            if self.report and fv.int8raw:
                self._emit(node, "DL027",
                           f"int8-tier page bytes flow into jitted "
                           f"`{name}` without dequantize_pages — the "
                           f"values are quantized codes, not KV rows")
        if self.report and self._injit == 0 and self._funcs:
            self.sites.append(FormSite(
                name, self.ms.path, getattr(node, "lineno", 0),
                self._funcs[0] == "warmup", self._qualname(),
                None if starred else len(node.args), tuple(arg_keys),
                tuple(sorted(kwargs)), kwstar, kwargs))
        return FormVal("?", "committed", "arr")

    # ------------------------------------------------------------ visitors

    def visit_Assign(self, node: ast.Assign) -> None:
        fv = self.eval(node.value)
        # q, s = quantize_pages(...): the scale must be used afterwards
        if isinstance(node.value, ast.Call):
            d = dotted(node.value.func)
            tail = d.rsplit(".", 1)[-1] if d else None
            if tail in _QUANT_FNS and len(node.targets) == 1 \
                    and isinstance(node.targets[0], (ast.Tuple, ast.List)) \
                    and len(node.targets[0].elts) == 2 \
                    and isinstance(node.targets[0].elts[1], ast.Name) \
                    and self.report:
                sname = node.targets[0].elts[1].id
                self._dropped_scales.setdefault(
                    sname, (getattr(node, "lineno", 0), node))
        for t in node.targets:
            self._bind_target(t, fv)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._bind_target(node.target, self.eval(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        val = self.eval(node.value)
        if isinstance(node.target, ast.Name):
            old = self._lookup(node.target.id)
            if isinstance(node.op, _ARITH_BINOPS):
                self._check_promotion(node, old, val)
            self._bind(node.target.id,
                       FormVal(_promote(old.dtype, val.dtype), old.prov,
                               old.kind, frozenset(), old.elem,
                               old.int8raw or val.int8raw))

    def _bind_target(self, t: ast.AST, fv: FormVal) -> None:
        if isinstance(t, ast.Name):
            self._bind(t.id, fv)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                # unpacking a call result: provenance flows to every
                # target; dtype does not
                self._bind_target(e, FormVal("?", fv.prov, "?",
                                             int8raw=fv.int8raw))
        elif isinstance(t, ast.Starred):
            self._bind_target(t.value, fv)
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            if isinstance(t, ast.Subscript):
                self.eval(t.value)

    def visit_For(self, node: ast.For) -> None:
        self.eval(node.iter)
        self._bind_target(node.target, self._elem(node.iter)
                          if isinstance(node.target, ast.Name)
                          else UNKNOWN_FV)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    visit_AsyncFor = visit_For

    def visit_If(self, node: ast.If) -> None:
        self.eval(node.test)
        if self._is_tier_test(node.test):
            self._tier.append("int8")
            for stmt in node.body:
                self.visit(stmt)
            self._tier.pop()
            self._tier.append("fp16")
            for stmt in node.orelse:
                self.visit(stmt)
            self._tier.pop()
            return
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    @staticmethod
    def _is_tier_test(test: ast.AST) -> bool:
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return False       # `if not int8:` inverts; stay out of it
        for sub in ast.walk(test):
            d = dotted(sub)
            if d is not None and d.rsplit(".", 1)[-1] == _TIER_FLAG:
                return True
        return False

    def visit_Expr(self, node: ast.Expr) -> None:
        self.eval(node.value)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self.eval(node.value)

    def visit_While(self, node: ast.While) -> None:
        self.eval(node.test)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def _visit_with(self, node) -> None:
        for item in node.items:
            self.eval(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Try(self, node: ast.Try) -> None:
        for stmt in (node.body + node.orelse + node.finalbody
                     + [s for h in node.handlers for s in h.body]):
            self.visit(stmt)

    def visit_Await(self, node: ast.Await) -> None:
        self.eval(node.value)

    def visit_Raise(self, node: ast.Raise) -> None:
        if node.exc is not None:
            self.eval(node.exc)

    def visit_Delete(self, node: ast.Delete) -> None:
        pass


# ------------------------------------------------------- DL026 form matching

def check_form_drift(sites: Sequence[FormSite],
                     listy: Sequence[ListySite],
                     sources: Sequence[ModuleSource]) -> List[Violation]:
    """Match every serving call form against the warmup forms of the
    same entry; any serving form with no match is a first-request
    compile. Only meaningful when the scanned tree has a warmup() —
    fixture trees without one would flag every entry."""
    name, summary = RULES["DL026"]
    by_path = {ms.path: ms for ms in sources}
    warm: Dict[str, List[FormSite]] = {}
    serve: Dict[str, List[FormSite]] = {}
    seen: Set[Tuple[str, str, int]] = set()
    for s in sites:
        key = (s.entry, s.path, s.line)
        if key in seen:
            continue
        seen.add(key)
        (warm if s.warm else serve).setdefault(s.entry, []).append(s)
    out: List[Violation] = []
    if not warm:
        return out

    def _sup(path: str, line: int) -> bool:
        ms = by_path.get(path)
        return ms is not None and _suppressed(ms, line, "DL026")

    for entry in sorted(serve):
        ssites = sorted(serve[entry], key=lambda s: (s.path, s.line))
        wsites = warm.get(entry)
        if not wsites:
            # folded-in DL015 coverage check: dispatched, never warmed
            s0 = ssites[0]
            if not _sup(s0.path, s0.line):
                out.append(Violation(
                    s0.path, s0.line, 0, "DL026", name,
                    f"{summary}: jitted entry `{entry}` is dispatched at "
                    f"serving time but never exercised by warmup() — its "
                    f"first call compiles mid-serving", entry))
            continue
        for s in ssites:
            best: Optional[List[str]] = None
            matched = False
            for w in wsites:
                diffs = _form_mismatches(s, w)
                if diffs is None:
                    continue
                if not diffs:
                    matched = True
                    break
                if best is None or len(diffs) < len(best):
                    best = diffs
            if matched or _sup(s.path, s.line):
                continue
            if best is None:
                why = (f"no warmup form has this arity/kwarg set "
                       f"(warmed: "
                       f"{'; '.join(w.render() for w in wsites[:2])})")
            else:
                why = "; ".join(best)
            out.append(Violation(
                s.path, s.line, 0, "DL026", name,
                f"{summary}: serving form `{s.render()}` has no warmup "
                f"match — {why} — the first serving call in this form "
                f"compiles mid-flight", entry))

    # the tiny-program sub-check: a serving-path jnp.asarray(<list>) with
    # no warmup list-convert of a compatible dtype
    warm_listy = [ls for ls in listy if ls.warm]
    for ls in sorted((ls for ls in listy if not ls.warm),
                     key=lambda s: (s.path, s.line)):
        if any(_dt_compat(ls.dtype, w.dtype) for w in warm_listy):
            continue
        if _sup(ls.path, ls.line):
            continue
        out.append(Violation(
            ls.path, ls.line, 0, "DL026", name,
            f"{summary}: `{ls.text}` converts a python list on the "
            f"serving path — one tiny convert program per distinct "
            f"padded length — and warmup() never exercises the "
            f"{ls.dtype} list-convert form", ls.scope))
    return out


# ------------------------------------------------------------------ driver

def analyze_form(sources: Sequence[ModuleSource],
                 graph: Optional[CallGraph] = None) -> List[Violation]:
    """Run the dynaform passes (DL025/DL026/DL027) over already-loaded
    modules, reusing a shared call graph when given."""
    from .callgraph import module_name
    from .dynahot import hot_regions

    if graph is None:
        graph = CallGraph.build(sources)
    jits = collect_jits(sources)
    hot_keys = set(hot_regions(graph, sources))
    out: List[Violation] = []
    sites: List[FormSite] = []
    listy: List[ListySite] = []
    for ms in sources:
        norm = ms.path.replace("\\", "/")
        if not any(m in norm for m in DEVICE_MODULE_MARKERS):
            continue
        scan = _FormScan(ms, module_name(ms.path), graph, jits, hot_keys)
        scan.visit(ms.tree)
        out.extend(scan.violations)
        sites.extend(scan.sites)
        listy.extend(scan.listy)
    out.extend(check_form_drift(sites, listy, sources))
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out
