"""dynajit: static compilation-stability & device-residency analysis
(DL015-DL017).

The engine's load-bearing invariant — *no XLA compile ever happens
mid-serving; ``warmup()`` pre-compiles the full bucket grid* — is pure
discipline: one unbucketed shape reaching a jitted call, one
request-varying ``static_argnames`` value, and every distinct value pays
a multi-second serve-time compile that stalls every in-flight request.
Donation discipline is just as silent: a donated buffer read after its
jit call is a correctness bug XLA only reports at runtime (and only
sometimes). This pass makes both checkable, on the same shared AST parse
and call graph as dynaflow/dynarace.

The analysis types values along two axes:

- **shape provenance** — ``BUCKETED`` (int literals, ``EngineConfig``
  /``ModelConfig`` attribute reads, and anything laundered through a
  bucket helper: ``bucket_batch``/``prefill_bucket_batch``/``bucket_len``
  /``bucket_pages``/``_pick``/``_long_bucket``/``_pad_pow2``), ``RAW``
  (request-varying: ``len(...)`` of request data, ``List``-annotated
  parameters, list comprehensions — their length is data-dependent), or
  ``UNKNOWN``. Only definitely-RAW shapes are reported: a whole-program
  lint must never guess.
- **device residency** — ``DEVICE`` (returns of jitted calls, the engine
  KV pools/params, ``jnp.*`` constructors and ops over device values) vs
  ``HOST`` (``np.*`` results, host pools, Python scalars) vs unknown.

Rules (tier-1-enforced with an EMPTY baseline):

- **DL015 recompile-hazard** — a jitted call site (a resolved
  ``@jax.jit`` function, or the engine's ``self.<name>_fn`` step-fn
  convention) taking an argument whose shape is RAW, a
  ``static_argnames``/``static_argnums`` value that is request-varying,
  or a device-pool gather (``self.kv_k[:, idx]``) whose index shape is
  RAW — each distinct shape/value is one serve-time XLA compile.
  (Warmup coverage — entries dispatched at serving time that
  ``warmup()`` never exercises — lives in dynaform's DL026 call-form
  matching, which subsumes the per-entry check this rule used to own.)
- **DL016 donation-discipline** — (a) a donated argument (the callee's
  ``donate_argnames``/``donate_argnums``, or the ``self.kv_k``/
  ``self.kv_v`` pool-donation convention of the step fns) that is
  neither rebound by the calling statement nor dead afterwards: the
  buffer is invalid the moment the call dispatches; (b) a jitted
  function that updates a parameter in place (``param.at[...]``) and
  returns it without donating it — XLA keeps a second copy of the
  buffer in HBM.
- **DL017 implicit-host-transfer** — a device-typed value flowing into
  a host-transfer sink (``np.asarray``/``np.array``/``.item()``/
  ``.tolist()``/``float()``/``int()``/``bool()``/iteration). Value-flow
  based, so it catches the assignments-then-sync shapes the
  callsite-pattern DL005 cannot — and stays quiet on ``np.asarray`` of
  host lists, which DL005's pattern match cannot distinguish. Applies
  to every non-jitted function in engine modules (``HOT_SYNC_ALLOWLIST``
  members excluded — they ARE the designed sync points), and
  chain-reports sinks reached from hot step functions through sync
  helpers, exactly like interprocedural DL005.

Suppression: the usual ``# dynalint: disable=<rule>`` on the line or the
line above. Policy (docs/static_analysis.md): fix RAW shapes by
laundering through a bucket helper; suppress only where the transfer or
the shape variance is the operation's documented purpose (e.g. the
disagg extract — the D2H *is* the product).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .analyzer import (HOT_SYNC_ALLOWLIST, RULES, ModuleSource,
                       Violation, call_attr, dotted)
from .callgraph import DEFAULT_DL008_DEPTH, CallGraph
from .dynahot import HOT_FRAME_RE

# ------------------------------------------------------------------- config

# modules scanned for jit definitions (DL016b) — the device-code tree
DEVICE_MODULE_MARKERS = ("engine/", "models/", "parallel/", "ops/")
# modules whose call sites are checked (DL015/016a/017) — the serving layer
ENGINE_MARKER = "engine/"

# shape-laundering helpers: their RESULT is bucketed regardless of input
# (that is their whole job). New helpers must be added here AND warmed.
BUCKET_HELPERS = frozenset({
    "bucket_batch", "prefill_bucket_batch", "bucket_len", "bucket_pages",
    "_pick", "_pad_pow2", "_long_bucket",
})
# attribute bases whose reads are config-static (never request-varying)
CONFIG_BASE_RE = re.compile(
    r"^(self\.)?(ecfg|cfg|mcfg|model_cfg|engine_cfg|config)$")
# self-attributes that are config-derived scalars
CONFIG_SELF_ATTRS = frozenset({"cap_pages", "cap_tokens", "spec_steps"})
# device pools (config-static shapes; kv_k/kv_v are donated by convention
# at every self.<name>_fn step call)
DEVICE_POOL_ATTRS = frozenset({"kv_k", "kv_v", "params"})
DONATED_POOL_ATTRS = frozenset({"kv_k", "kv_v"})
HOST_POOL_ATTRS = frozenset({"host_k", "host_v", "host_k_s", "host_v_s"})
# the engine step-fn convention: `self.<x>_fn(...)` is a jitted entry
JIT_ATTR_RE = re.compile(r"_fn$")

NP_BASES = ("np", "numpy")
JNP_BASES = ("jnp", "jax.numpy")
CONSTRUCTORS = frozenset({"zeros", "full", "ones", "empty", "arange"})
ELEMENTWISE = frozenset({"where", "minimum", "maximum", "clip", "mod"})
TRANSFER_SINK_ATTRS = frozenset({"item", "tolist"})
TRANSFER_SINK_BUILTINS = frozenset({"float", "int", "bool"})
LIST_ANNOT_RE = re.compile(r"^(typing\.)?(List|Sequence|list)\b")

# provenance lattice: B (bucketed/static) < U (unknown) < R (raw)
B, U, R = 0, 1, 2
# residency
DEV, HOST, UNK = "dev", "host", "unk"

_SCALAR = object()  # shape sentinel for scalar-valued expressions


def _join(*provs: int) -> int:
    return max(provs) if provs else U


@dataclass
class Prov:
    """(dim, shape, residency, elem) for one expression.

    ``dim`` — provenance of the VALUE used as an array dimension;
    ``shape`` — provenance of the expression's own array shape
    (B for scalars: a scalar's shape is statically ``()``);
    ``dev`` — device residency; ``elem`` — provenance of the elements
    when the value is iterated (loop targets inherit it)."""

    dim: int = 1            # U
    shape: int = 1          # U
    dev: str = UNK
    elem: Optional["Prov"] = None

    @staticmethod
    def bucketed(dev: str = HOST) -> "Prov":
        return Prov(B, B, dev, None)

    @staticmethod
    def raw(dev: str = UNK) -> "Prov":
        return Prov(R, R, dev, None)


UNKNOWN = Prov()


@dataclass
class JitInfo:
    """Statically-extracted jit metadata for one decorated function."""

    key: str                 # callgraph key '<module>:<qualname>'
    name: str
    path: str
    lineno: int
    params: List[str] = field(default_factory=list)
    static_names: Set[str] = field(default_factory=set)
    static_nums: Set[int] = field(default_factory=set)
    donate_names: Set[str] = field(default_factory=set)
    donate_nums: Set[int] = field(default_factory=set)

    def donated_params(self) -> Set[str]:
        out = set(self.donate_names)
        for i in self.donate_nums:
            if 0 <= i < len(self.params):
                out.add(self.params[i])
        return out

    def static_params(self) -> Set[str]:
        out = set(self.static_names)
        for i in self.static_nums:
            if 0 <= i < len(self.params):
                out.add(self.params[i])
        return out


# --------------------------------------------------------- jit collection

def _literal_set(node: ast.AST) -> Optional[Tuple]:
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(v, (str, int)):
        return (v,)
    if isinstance(v, (tuple, list, set)):
        return tuple(v)
    return None


def _jit_decorator_kw(dec: ast.AST) -> Optional[List[ast.keyword]]:
    """``@jax.jit`` → []; ``@partial(jax.jit, ...)`` /
    ``@functools.partial(jax.jit, ...)`` → its keywords; else None."""
    if isinstance(dec, ast.Attribute) or isinstance(dec, ast.Name):
        if dotted(dec) in ("jax.jit", "jit"):
            return []
        return None
    if not isinstance(dec, ast.Call):
        return None
    d = dotted(dec.func)
    if d in ("jax.jit", "jit"):
        return dec.keywords
    if d in ("partial", "functools.partial") and dec.args \
            and dotted(dec.args[0]) in ("jax.jit", "jit"):
        return dec.keywords
    return None


class _JitCollector(ast.NodeVisitor):
    """Find every jit-decorated def in a module (including nested defs
    inside builder functions) and its static/donate metadata."""

    def __init__(self, ms: ModuleSource, modname: str):
        self.ms = ms
        self.modname = modname
        self.jits: Dict[str, JitInfo] = {}   # key -> info
        self._stack: List[str] = []

    def _visit_func(self, node) -> None:
        qual = ".".join(self._stack + [node.name])
        kw = None
        for dec in node.decorator_list:
            kw = _jit_decorator_kw(dec)
            if kw is not None:
                break
        if kw is not None:
            info = JitInfo(key=f"{self.modname}:{qual}", name=node.name,
                           path=self.ms.path, lineno=node.lineno,
                           params=[a.arg for a in node.args.posonlyargs
                                   + node.args.args])
            for k in kw:
                vals = _literal_set(k.value) if k.arg else None
                if vals is None:
                    continue
                if k.arg == "static_argnames":
                    info.static_names |= {v for v in vals
                                          if isinstance(v, str)}
                elif k.arg == "static_argnums":
                    info.static_nums |= {v for v in vals
                                         if isinstance(v, int)}
                elif k.arg == "donate_argnames":
                    info.donate_names |= {v for v in vals
                                          if isinstance(v, str)}
                elif k.arg == "donate_argnums":
                    info.donate_nums |= {v for v in vals
                                         if isinstance(v, int)}
            self.jits[info.key] = info
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()


def collect_jits(sources: Sequence[ModuleSource]) -> Dict[str, JitInfo]:
    from .callgraph import module_name

    jits: Dict[str, JitInfo] = {}
    for ms in sources:
        norm = ms.path.replace("\\", "/")
        if not any(m in norm for m in DEVICE_MODULE_MARKERS):
            continue
        c = _JitCollector(ms, module_name(ms.path))
        c.visit(ms.tree)
        jits.update(c.jits)
    return jits


# ------------------------------------------------------- DL016(b) def check

def check_undonated_writes(sources: Sequence[ModuleSource],
                           jits: Dict[str, JitInfo]) -> List[Violation]:
    """A jitted def that updates a param via ``param.at[...]`` and
    returns it without donating it keeps two copies of the buffer in
    HBM. Reported at the def."""
    name, summary = RULES["DL016"]
    by_path: Dict[str, ModuleSource] = {ms.path: ms for ms in sources}
    out: List[Violation] = []
    for key in sorted(jits):
        info = jits[key]
        ms = by_path.get(info.path)
        if ms is None:
            continue
        node = _find_def(ms.tree, info)
        if node is None:
            continue
        donated = info.donated_params()
        written: Set[str] = set()
        returned: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr == "at" \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in info.params:
                written.add(sub.value.id)
            if isinstance(sub, ast.Return) and sub.value is not None:
                for n in ast.walk(sub.value):
                    if isinstance(n, ast.Name):
                        returned.add(n.id)
        for p in sorted((written & returned) - donated):
            if _suppressed(ms, info.lineno, "DL016"):
                continue
            out.append(Violation(
                info.path, info.lineno, 0, "DL016", name,
                f"{summary}: jitted `{info.name}` updates param `{p}` via "
                f".at[] and returns it without donating it — add it to "
                f"donate_argnames so XLA aliases the buffer in place",
                info.name))
    return out


def _find_def(tree: ast.AST, info: JitInfo):
    for sub in ast.walk(tree):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and sub.name == info.name and sub.lineno >= info.lineno - 8 \
                and sub.lineno <= info.lineno + 8:
            return sub
    return None


# ----------------------------------------------------------- the flow scan

def _suppressed(ms: ModuleSource, line: int, code: str) -> bool:
    name = RULES[code][0]
    for probe in (line, line - 1):
        tags = ms.suppressed.get(probe)
        if tags and (code in tags or name in tags or "all" in tags):
            return True
    return False


def _allowlisted(qualname: str) -> bool:
    return qualname in HOT_SYNC_ALLOWLIST or any(
        qualname.startswith(a + ".") for a in HOT_SYNC_ALLOWLIST)


@dataclass
class FuncJitScan:
    """Per-function results: DL017 sink records for chain propagation."""

    key: str
    qualname: str
    transfer_sinks: List[Tuple[int, str]] = field(default_factory=list)


class _FlowScan(ast.NodeVisitor):
    """One ENGINE module: provenance/residency dataflow over every
    non-jitted function (nested defs share the enclosing scope chain —
    closures read outer locals), emitting DL015/DL016(a)/DL017."""

    def __init__(self, ms: ModuleSource, modname: str, graph: CallGraph,
                 jits: Dict[str, JitInfo]):
        self.ms = ms
        self.modname = modname
        self.graph = graph
        self.jits = jits
        # direct violations only in the serving layer (engine modules);
        # models/parallel/ops modules still contribute DL017 sink records
        # so hot engine functions chain-report transfers they reach
        self.report = ENGINE_MARKER in ms.path.replace("\\", "/")
        self.violations: List[Violation] = []
        self.func_scans: Dict[str, FuncJitScan] = {}
        # jitted entries called from serving code / from warmup bodies:
        # display-name -> representative (path, line)
        self.serving_entries: Dict[str, Tuple[str, int]] = {}
        self.warmed_entries: Set[str] = set()
        self._classes: List[str] = []
        self._funcs: List[str] = []
        self._scopes: List[Dict[str, Prov]] = []
        self._scan: List[Optional[FuncJitScan]] = []
        self._mod = graph.modules.get(modname)

    # ------------------------------------------------------------ plumbing

    def _qualname(self) -> str:
        return ".".join(self._classes + self._funcs) or "<module>"

    def _emit(self, node: ast.AST, code: str, detail: str) -> None:
        if not self.report:
            return
        line = getattr(node, "lineno", 0)
        if _suppressed(self.ms, line, code):
            return
        name, summary = RULES[code]
        self.violations.append(Violation(
            self.ms.path, line, getattr(node, "col_offset", 0), code,
            name, f"{summary}: {detail}", self._qualname()))

    # ------------------------------------------------------------- scoping

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    def _visit_func(self, node) -> None:
        # jitted bodies trace on device — host-transfer/provenance rules
        # do not apply inside them (DL016b covers their discipline)
        if any(_jit_decorator_kw(d) is not None
               for d in node.decorator_list):
            return
        qual = ".".join(self._classes + self._funcs + [node.name])
        fs = FuncJitScan(key=f"{self.modname}:{qual}", qualname=qual)
        self.func_scans[fs.key] = fs
        scope: Dict[str, Prov] = {}
        for a in node.args.posonlyargs + node.args.args + [
                node.args.vararg, node.args.kwarg] + node.args.kwonlyargs:
            if a is None:
                continue
            ann = ast.unparse(a.annotation) if a.annotation else ""
            if LIST_ANNOT_RE.match(ann) and "ndarray" not in ann:
                scope[a.arg] = Prov(R, R, HOST)
            else:
                scope[a.arg] = UNKNOWN
        self._funcs.append(node.name)
        self._scopes.append(scope)
        self._scan.append(fs)
        for stmt in node.body:
            self.visit(stmt)
        self._scan.pop()
        self._scopes.pop()
        self._funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _lookup(self, name: str) -> Prov:
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return UNKNOWN

    def _bind(self, name: str, prov: Prov) -> None:
        if self._scopes:
            old = self._scopes[-1].get(name)
            if old is not None and old is not UNKNOWN:
                # flow-insensitive join of re-assignments
                prov = Prov(_join(old.dim, prov.dim),
                            _join(old.shape, prov.shape),
                            prov.dev if prov.dev == old.dev else UNK,
                            prov.elem or old.elem)
            self._scopes[-1][name] = prov

    # -------------------------------------------------------- the evaluator

    def eval(self, node: Optional[ast.AST]) -> Prov:  # noqa: C901
        if node is None:
            return Prov.bucketed()
        if isinstance(node, ast.Constant):
            return Prov(B, B, HOST)
        if isinstance(node, ast.Name):
            return self._lookup(node.id)
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.Tuple) or isinstance(node, ast.Set):
            elts = [self.eval(e) for e in node.elts]
            return Prov(_join(*[p.dim for p in elts]) if elts else B,
                        _join(*[p.shape for p in elts]) if elts else B,
                        DEV if any(p.dev == DEV for p in elts) else HOST
                        if all(p.dev == HOST for p in elts) else UNK,
                        elts[0] if elts else None)
        if isinstance(node, ast.List):
            # display: a FIXED number of elements — static length
            elts = [self.eval(e) for e in node.elts]
            return Prov(U, B, HOST, elts[0] if elts else None)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            # data-dependent length
            elem = (self._elem_of(node.generators[0].iter)
                    if isinstance(node.elt, ast.Name)
                    and node.generators and isinstance(
                        node.generators[0].target, ast.Name)
                    and node.elt.id == node.generators[0].target.id
                    else self.eval(node.elt))
            return Prov(U, R, HOST, elem)
        if isinstance(node, ast.IfExp):
            a, b = self.eval(node.body), self.eval(node.orelse)
            return Prov(_join(a.dim, b.dim), _join(a.shape, b.shape),
                        a.dev if a.dev == b.dev else UNK, a.elem)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.BoolOp):
            provs = [self.eval(v) for v in node.values]
            return Prov(_join(*[p.dim for p in provs]),
                        _join(*[p.shape for p in provs]), UNK, None)
        if isinstance(node, ast.Compare):
            shapes = [self.eval(node.left).shape] + \
                [self.eval(c).shape for c in node.comparators]
            return Prov(U, _join(*shapes), UNK, None)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        return UNKNOWN

    def _eval_attr(self, node: ast.Attribute) -> Prov:
        base = dotted(node.value)
        if base is not None and CONFIG_BASE_RE.match(base):
            return Prov(B, B, HOST)
        if base in ("self",):
            if node.attr in DEVICE_POOL_ATTRS:
                return Prov(U, B, DEV)
            if node.attr in HOST_POOL_ATTRS:
                return Prov(U, B, HOST)
            if node.attr in CONFIG_SELF_ATTRS:
                return Prov(B, B, HOST)
        # any other attribute read: request-varying as a DIMENSION value,
        # unknown as an array
        return Prov(R, U, UNK)

    def _elem_of(self, node: ast.AST) -> Prov:
        if isinstance(node, ast.Attribute):
            base = dotted(node.value)
            if base is not None and CONFIG_BASE_RE.match(base):
                return Prov(B, B, HOST)
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            provs = [self.eval(e) for e in node.elts]
            return Prov(_join(*[p.dim for p in provs]) if provs else B,
                        _join(*[p.shape for p in provs]) if provs else B,
                        HOST, None)
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            tail = d.rsplit(".", 1)[-1] if d else None
            if tail in ("sorted", "set", "list", "tuple", "reversed") \
                    and node.args:
                return self._elem_of(node.args[0])
            if tail == "range":
                return Prov(_join(*[self.eval(a).dim for a in node.args]),
                            B, HOST)
            if tail == "enumerate" or tail == "zip":
                return UNKNOWN
        if isinstance(node, ast.Name):
            p = self._lookup(node.id)
            return p.elem or UNKNOWN
        p = self.eval(node)
        return p.elem or UNKNOWN

    def _eval_binop(self, node: ast.BinOp) -> Prov:
        left, right = self.eval(node.left), self.eval(node.right)
        # scalar/static-shaped operands broadcast: join the non-static
        # operand shapes (a raw-length list concatenation stays raw)
        shapes = [p.shape for p in (left, right) if p.shape != B]
        shape = _join(*shapes) if shapes else B
        dev = DEV if DEV in (left.dev, right.dev) else (
            HOST if left.dev == right.dev == HOST else UNK)
        return Prov(_join(left.dim, right.dim), shape, dev, left.elem)

    def _eval_subscript(self, node: ast.Subscript) -> Prov:
        value = self.eval(node.value)
        idx = node.slice
        parts = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
        arr_parts = [p for p in parts if not isinstance(
            p, (ast.Slice, ast.Constant))]
        if not arr_parts:
            # pure slicing / constant index: view of, or element of, the
            # subscripted value
            if any(isinstance(p, ast.Slice) for p in parts):
                return Prov(value.dim, value.shape, value.dev, value.elem)
            return value.elem or Prov(U, U, value.dev)
        ip = [self.eval(p) for p in arr_parts]
        ishape = _join(*[p.shape for p in ip])
        # a gather's result shape follows the INDEX shape: a raw-length
        # index into a device pool is one XLA compile per distinct length
        if value.dev == DEV and ishape == R:
            self._emit(node, "DL015",
                       f"device gather `{ast.unparse(node)[:60]}` with a "
                       f"request-varying index shape — each distinct "
                       f"length is one XLA compile; pad through "
                       f"`_pad_pow2`/a bucket helper")
        return Prov(U, ishape, value.dev, None)

    # ---------------------------------------------------------------- calls

    def _jit_callee(self, node: ast.Call) -> Tuple[Optional[str],
                                                   Optional[JitInfo]]:
        """(display-name, JitInfo|None) when this is a jitted call site."""
        d = dotted(node.func)
        if d is None:
            return None, None
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2 \
                and JIT_ATTR_RE.search(parts[1]):
            return parts[1], None          # step-fn convention
        # resolved project function with jit metadata
        if self._mod is not None:
            first = self._qualname().split(".")[0]
            cls_name = first if first in self._mod.classes else None
            fi = self._mod.functions.get(self._qualname())
            fi_key = self.graph._resolve(
                self._mod, d, cls_name, fi if fi is not None else _DUMMY_FI)
            if fi_key is not None and fi_key in self.jits:
                return d.rsplit(".", 1)[-1], self.jits[fi_key]
        return None, None

    def _eval_call(self, node: ast.Call) -> Prov:  # noqa: C901
        d = dotted(node.func)
        tail = d.rsplit(".", 1)[-1] if d else call_attr(node)
        base = d.rsplit(".", 1)[0] if d and "." in d else None

        if tail in BUCKET_HELPERS:
            for a in node.args:
                self.eval(a)
            return Prov(B, B, HOST, Prov(B, B, HOST))
        if base not in NP_BASES and base not in JNP_BASES:
            if tail == "len":
                return Prov(R, B, HOST)
            if tail in ("min", "max", "sum", "abs", "round"):
                provs = [self.eval(a) for a in node.args]
                return Prov(_join(*[p.dim for p in provs]) if provs else U,
                            _join(*[p.shape for p in provs]) if provs
                            else B, HOST, None)
            if tail in ("sorted", "set", "list", "tuple") and node.args:
                inner = self.eval(node.args[0])
                return Prov(U, inner.shape, HOST,
                            self._elem_of(node.args[0]))

        if base in NP_BASES or base in JNP_BASES:
            dev = DEV if base in JNP_BASES else HOST
            if tail in CONSTRUCTORS:
                shape = self._shape_arg_prov(node)
                return Prov(U, shape, dev)
            if tail in ("asarray", "array"):
                src = self.eval(node.args[0]) if node.args else UNKNOWN
                if dev == HOST and src.dev == DEV:
                    self._transfer_sink(node, f"`{d}(...)` on a "
                                              f"device value")
                return Prov(src.dim, src.shape, dev, src.elem)
            if tail in ELEMENTWISE:
                provs = [self.eval(a) for a in node.args]
                shapes = [p.shape for p in provs
                          if p.shape != B]  # scalars broadcast away
                return Prov(U, _join(*shapes) if shapes else B, dev)
            if tail == "bincount" or tail == "unique":
                for a in node.args:
                    self.eval(a)
                return Prov(U, U, dev)
            for a in node.args:
                self.eval(a)
            return Prov(U, U, dev)

        # host-transfer builtin sinks: float(dev) / int(dev) / bool(dev)
        if isinstance(node.func, ast.Name) \
                and node.func.id in TRANSFER_SINK_BUILTINS and node.args:
            src = self.eval(node.args[0])
            if src.dev == DEV:
                self._transfer_sink(node, f"`{node.func.id}()` on a "
                                          f"device value")
            return Prov(U, B, HOST)
        # .item() / .tolist() on a device value
        if call_attr(node) in TRANSFER_SINK_ATTRS \
                and isinstance(node.func, ast.Attribute):
            src = self.eval(node.func.value)
            if src.dev == DEV:
                self._transfer_sink(node, f"`.{call_attr(node)}()` on a "
                                          f"device value")
            return Prov(U, B, HOST)

        jit_name, info = self._jit_callee(node)
        if jit_name is not None:
            return self._check_jit_call(node, jit_name, info)

        for a in node.args:
            self.eval(a)
        for k in node.keywords:
            self.eval(k.value)
        # a method call on a device receiver stays on device (.sum(),
        # .astype(), .reshape(), ...) — .item()/.tolist() were handled
        # above as transfer sinks
        if isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
            if recv.dev == DEV:
                return Prov(U, U, DEV)
        return UNKNOWN

    def _shape_arg_prov(self, node: ast.Call) -> int:
        """np.zeros(shape)/np.full(shape, fill)/np.arange(a[, b]): the
        result's shape provenance comes from the DIM values."""
        tail = (dotted(node.func) or "").rsplit(".", 1)[-1]
        args = node.args[:2] if tail == "arange" else node.args[:1]
        dims: List[int] = []
        for a in args:
            if isinstance(a, ast.Tuple):
                for e in a.elts:
                    if isinstance(e, ast.Starred):
                        p = self.eval(e.value)
                        dims.append(_join(p.dim, p.shape))
                    else:
                        dims.append(self.eval(e).dim)
            else:
                p = self.eval(a)
                # a shape TUPLE variable: its element values are the dims
                dims.append(p.dim if p.elem is None else
                            _join(p.dim, p.elem.dim))
        return _join(*dims) if dims else U

    def _check_jit_call(self, node: ast.Call, name: str,
                        info: Optional[JitInfo]) -> Prov:
        """DL015 shape/static-value checks + DL016(a) donation checks at
        one jitted call site; result is device-resident with the join of
        the argument shape provenances."""
        self._note_entry(name, node)
        arg_provs: List[Prov] = []
        static_params = info.static_params() if info else set()
        static_nums = info.static_nums if info else set()
        params = info.params if info else []
        for i, a in enumerate(node.args):
            p = self.eval(a)
            arg_provs.append(p)
            pname = params[i] if i < len(params) else None
            if i in static_nums or (pname and pname in static_params):
                if p.dim == R:
                    self._emit(node, "DL015",
                               f"static arg {i} of `{name}` takes a "
                               f"request-varying value — every distinct "
                               f"value is one serve-time XLA compile")
                continue
            if p.shape == R:
                self._emit(node, "DL015",
                           f"arg {i} (`{ast.unparse(a)[:48]}`) of jitted "
                           f"`{name}` has a request-varying shape — "
                           f"launder it through a bucket helper "
                           f"(bucket_batch/bucket_len/bucket_pages/"
                           f"_pad_pow2)")
        for k in node.keywords:
            p = self.eval(k.value)
            if k.arg and k.arg in static_params and p.dim == R:
                self._emit(node, "DL015",
                           f"static arg `{k.arg}` of `{name}` takes a "
                           f"request-varying value — every distinct "
                           f"value is one serve-time XLA compile")
            elif k.arg and p.shape == R:
                self._emit(node, "DL015",
                           f"arg `{k.arg}` of jitted `{name}` has a "
                           f"request-varying shape — launder it through "
                           f"a bucket helper")
        self._check_donation(node, name, info)
        shape = _join(*[p.shape for p in arg_provs if p.shape != B]) \
            if any(p.shape != B for p in arg_provs) else B
        return Prov(U, shape, DEV)

    # ------------------------------------------------------ DL016(a) calls

    def _check_donation(self, node: ast.Call, name: str,
                        info: Optional[JitInfo]) -> None:
        donated: List[ast.AST] = []
        if info is not None:
            dparams = info.donated_params()
            for i, a in enumerate(node.args):
                pname = info.params[i] if i < len(info.params) else None
                if pname in dparams or i in info.donate_nums:
                    donated.append(a)
        else:
            # engine step-fn convention: the KV pools are donated
            for a in node.args:
                if isinstance(a, ast.Attribute) \
                        and isinstance(a.value, ast.Name) \
                        and a.value.id == "self" \
                        and a.attr in DONATED_POOL_ATTRS:
                    donated.append(a)
        if not donated:
            return
        stmt = node
        parent = getattr(node, "_dl_parent", None)
        while parent is not None and not isinstance(parent, ast.stmt):
            stmt = parent
            parent = getattr(parent, "_dl_parent", None)
        stmt = parent if isinstance(parent, ast.stmt) else stmt
        rebound: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for n in ast.walk(t):
                    nd = dotted(n)
                    if nd:
                        rebound.add(nd)
        fn_node = self._enclosing_fn_node(node)
        after = getattr(stmt, "end_lineno", None) or \
            getattr(node, "end_lineno", node.lineno)
        for a in donated:
            ad = dotted(a)
            if ad is None or ad in rebound:
                continue
            use = self._load_after(fn_node, ad, after) \
                if fn_node is not None else None
            if use is not None:
                self._emit(use, "DL016",
                           f"`{ad}` was donated to `{name}` at line "
                           f"{node.lineno} and is used here afterwards — "
                           f"the buffer is invalid once the call "
                           f"dispatches; rebind it from the call's "
                           f"result")

    def _enclosing_fn_node(self, node: ast.AST):
        cur = getattr(node, "_dl_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_dl_parent", None)
        return None

    def _load_after(self, fn_node, name: str, line: int):
        """First Load of ``name`` after ``line`` with no intervening
        rebinding store (textual order — the donated-use-after shape)."""
        events: List[Tuple[int, int, str, ast.AST]] = []
        for sub in ast.walk(fn_node):
            nd = dotted(sub)
            if nd != name:
                continue
            ln = getattr(sub, "lineno", 0)
            if ln <= line:
                continue
            ctx = getattr(sub, "ctx", None)
            kind = "store" if isinstance(ctx, ast.Store) else "load"
            events.append((ln, getattr(sub, "col_offset", 0), kind, sub))
        for ln, _col, kind, sub in sorted(events, key=lambda e: (e[0],
                                                                 e[1])):
            if kind == "store":
                return None
            return sub
        return None

    # --------------------------------------------------- DL017 + coverage

    def _transfer_sink(self, node: ast.AST, what: str) -> None:
        qual = self._qualname()
        if _allowlisted(qual):
            return
        line = getattr(node, "lineno", 0)
        if self._scan and self._scan[-1] is not None:
            if not _suppressed(self.ms, line, "DL017"):
                self._scan[-1].transfer_sinks.append((line, what))
        # direct report for non-jitted ENGINE functions (_emit no-ops
        # elsewhere); sinks in models/parallel/ops chain-report at the
        # hot engine call site via check_transitive_transfer
        self._emit(node, "DL017", what)

    def _note_entry(self, name: str, node: ast.AST) -> None:
        if not self.report:
            return  # serving/warmed entries are an engine-layer notion
        fn = self._funcs[0] if self._funcs else "<module>"
        if fn == "warmup":
            self.warmed_entries.add(name)
        else:
            self.serving_entries.setdefault(
                name, (self.ms.path, getattr(node, "lineno", 0)))

    # ------------------------------------------------------------ visitors

    def visit_Assign(self, node: ast.Assign) -> None:
        prov = self.eval(node.value)
        for t in node.targets:
            self._bind_target(t, prov)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._bind_target(node.target, self.eval(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        prov = self.eval(node.value)
        if isinstance(node.target, ast.Name):
            old = self._lookup(node.target.id)
            self._bind(node.target.id,
                       Prov(_join(old.dim, prov.dim),
                            _join(old.shape if old.shape != B else B,
                                  B if prov.shape == B else prov.shape),
                            old.dev, old.elem))

    def _bind_target(self, t: ast.AST, prov: Prov) -> None:
        if isinstance(t, ast.Name):
            self._bind(t.id, prov)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                # tuple-unpack of a call result: residency flows to every
                # target (out_d, acc_d = verify_greedy_draft(...))
                self._bind_target(e, Prov(U, U, prov.dev))
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            self.eval(t.value if isinstance(t, ast.Attribute) else t.value)

    def visit_For(self, node: ast.For) -> None:
        it = self.eval(node.iter)
        # a tuple/list LITERAL of device values is host iteration over
        # array objects, not a device sync
        if it.dev == DEV and not isinstance(
                node.iter, (ast.Tuple, ast.List, ast.Set)):
            self._transfer_sink(node, "iteration over a device value "
                                      "syncs every element to host")
        elem = self._elem_of(node.iter)
        self._bind_target(node.target,
                          elem if isinstance(node.target, ast.Name)
                          else UNKNOWN)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    visit_AsyncFor = visit_For

    def visit_Expr(self, node: ast.Expr) -> None:
        self.eval(node.value)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self.eval(node.value)

    def visit_If(self, node: ast.If) -> None:
        self.eval(node.test)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        self.eval(node.test)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def _visit_with(self, node) -> None:
        for item in node.items:
            self.eval(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Try(self, node: ast.Try) -> None:
        for stmt in (node.body + node.orelse + node.finalbody
                     + [s for h in node.handlers for s in h.body]):
            self.visit(stmt)

    def visit_Await(self, node: ast.Await) -> None:
        self.eval(node.value)

    def visit_Raise(self, node: ast.Raise) -> None:
        if node.exc is not None:
            self.eval(node.exc)

    def visit_Delete(self, node: ast.Delete) -> None:
        pass


class _DummyFI:
    qualname = "<module>"
    calls: List = []


_DUMMY_FI = _DummyFI()


# ---------------------------------------------------- chain DL017 reporting

def check_transitive_transfer(graph: CallGraph,
                              scans: Dict[str, FuncJitScan],
                              max_depth: int = DEFAULT_DL008_DEPTH
                              ) -> List[Violation]:
    """DL017 sinks reached from an engine hot-path (step) function
    through sync helpers fire at the hot call site with the full chain —
    the same shape as interprocedural DL005, sharing its allowlist."""
    reach: Dict[str, Tuple[int, List[str], str, int, str]] = {}
    for key, fs in scans.items():
        fi = graph.functions.get(key)
        if fi is None or fi.is_async or _allowlisted(fs.qualname) \
                or not fs.transfer_sinks:
            continue
        line, what = fs.transfer_sinks[0]
        reach[key] = (0, [key], fi.path, line, what)
    changed = True
    while changed:
        changed = False
        for fi in graph.functions.values():
            if fi.is_async or _allowlisted(fi.qualname):
                continue
            for cs in fi.calls:
                sub = reach.get(cs.target) if cs.target else None
                if sub is None:
                    continue
                callee = graph.functions.get(cs.target)
                if callee is None or callee.is_async \
                        or _allowlisted(callee.qualname):
                    continue
                depth = sub[0] + 1
                cur = reach.get(fi.key)
                if depth <= max_depth and (cur is None or depth < cur[0]):
                    reach[fi.key] = (depth, [fi.key] + sub[1], sub[2],
                                     sub[3], sub[4])
                    changed = True

    name, summary = RULES["DL017"]
    out: List[Violation] = []
    seen: Set[Tuple[str, str]] = set()
    for fi in graph.functions.values():
        if ENGINE_MARKER not in fi.path.replace("\\", "/"):
            continue
        if not HOT_FRAME_RE.search(fi.name) or _allowlisted(fi.qualname):
            continue
        mod = graph.modules[fi.module]
        for cs in fi.calls:
            sub = reach.get(cs.target) if cs.target else None
            if sub is None or cs.target == fi.key:
                continue
            callee = graph.functions.get(cs.target)
            if sub[0] == 0 and callee is not None and ENGINE_MARKER in \
                    callee.path.replace("\\", "/"):
                continue  # engine sinks were already reported directly
            if callee is not None and HOT_FRAME_RE.search(callee.name):
                continue
            if (fi.key, cs.target) in seen:
                continue
            seen.add((fi.key, cs.target))
            suppressed = False
            for probe in (cs.line, cs.line - 1):
                tags = mod.suppressed.get(probe)
                if tags and ({"DL017", name, "all"} & tags):
                    suppressed = True
            if suppressed:
                continue
            chain = " -> ".join(k.split(":", 1)[1] for k in sub[1])
            out.append(Violation(
                fi.path, cs.line, cs.col, "DL017", name,
                f"{summary}: `{cs.raw}` reaches {sub[4]} via {chain} "
                f"({sub[2]}:{sub[3]})", fi.qualname))
    return out


# ------------------------------------------------------------------ driver

def analyze_jit(sources: Sequence[ModuleSource],
                graph: Optional[CallGraph] = None) -> List[Violation]:
    """Run the dynajit passes (DL015/DL016/DL017) over already-loaded
    modules, reusing a shared call graph when given. Warmup coverage —
    which jitted entries serving dispatches that warmup() never
    exercises — moved to dynaform's DL026, where it is subsumed by full
    call-form matching (dtype/provenance/kwarg-set per site)."""
    from .callgraph import module_name

    if graph is None:
        graph = CallGraph.build(sources)
    jits = collect_jits(sources)
    out: List[Violation] = []
    out.extend(check_undonated_writes(sources, jits))
    scans: Dict[str, FuncJitScan] = {}
    for ms in sources:
        norm = ms.path.replace("\\", "/")
        if not any(m in norm for m in DEVICE_MODULE_MARKERS):
            continue
        scan = _FlowScan(ms, module_name(ms.path), graph, jits)
        scan.visit(ms.tree)
        out.extend(scan.violations)
        scans.update(scan.func_scans)
    out.extend(check_transitive_transfer(graph, scans))
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out
