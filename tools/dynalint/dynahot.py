"""dynahot: static hot-path cost & unbounded-growth analysis.

dynaturbo (PR 16) bought its decode tok/s by hand-profiling the per-token
host work; nothing in DL001-DL021 stops the next PR from silently
re-adding a per-token allocation, an eager f-string on the emit path, or
an unbounded dict that leaks under millions-of-users churn. dynahot makes
the hot path a machine-checked *cost* invariant over the shared PR 5/8
parse + callgraph:

- **HOT_ROOTS** below is the declared, pure-literal registry of hot-path
  roots — it replaces the old name-regex heuristic (``HOT_RE`` in
  analyzer.py, now derived here as ``HOT_FRAME_RE`` from the registry's
  ``frame_name_segments`` grammar, behavior pinned by test).
  *Scheduler-iteration* roots run once per engine step; *per-token*
  roots run once per emitted token / stream chunk.
- **Hot regions** are computed by callgraph reachability from the roots,
  with per-frame loop depth: a callee invoked from inside a loop of a
  hot frame inherits that loop's iteration count (``CallSite.loop_depth``
  accumulates into ``HotFrame.depth``). ``self.<attr>.<method>()`` calls
  resolve through one level of constructor typing (``self.pm =
  PageManager(...)`` in ``__init__``) so the region follows the engine
  into its collaborators instead of stopping at the attribute wall.

Three rules run over the region (tier-1, EMPTY baseline):

- **DL022 hot-loop-invariant-work** — loop-invariant rebuilds inside hot
  loops: ``<chain> or []`` invariant-default rebuilds, ``re.compile`` /
  ``struct.Struct`` / constant ``jnp.asarray`` in a loop, ``sorted()``
  of a loop-invariant name, the same deep attribute chain resolved 3+
  times in one frame, and exception-probe loop discovery
  (``try: asyncio.get_running_loop() except RuntimeError``) per call.
- **DL023 hot-eager-format** — eager f-string / %-format / ``str()``-of-
  structure handed to a logging/trace call on a hot frame without a
  sampling or level guard (same guard grammar as DL018).
- **DL024 unbounded-growth** — a ``self.<attr>`` collection mutated via
  ``append`` / ``[k]=`` / ``add`` from a hot (request-path) frame with
  no reachable removal, bound check, ring (``deque(maxlen=...)``), or
  reset anywhere in its class. Suppress with a justification comment:
  ``# bounded-by: <reason>`` on the mutation line (or the line above).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .analyzer import (LOG_METHODS, RULES, ModuleSource, Violation,
                       _is_sample_guard, call_attr, dotted)
from .callgraph import CallGraph

# --------------------------------------------------------------- registry

# The declared hot-path root registry (pure literal — tooling and tests
# read it with ast.literal_eval, serving code never imports it).
#
# - "scheduler": frames entered once per engine scheduler iteration.
# - "per_token": frames entered once per emitted token / stream chunk /
#   routed request — the tightest loops in the product.
# - "frame_name_segments": the legacy name grammar DL005 was built on
#   (analyzer.py's old HOT_RE): any engine function with one of these
#   segments in its snake_case name is a hot frame by name. Kept so
#   DL005's per-file + interprocedural behavior is EXACTLY what it was
#   (pinned by test_hot_frame_re_matches_legacy_hot_re).
HOT_ROOTS = {
    "scheduler": [
        "dynamo_tpu.engine.jax_engine:JaxEngine._step",
        "dynamo_tpu.engine.jax_engine:JaxEngine._loop",
        "dynamo_tpu.engine.jax_engine:JaxEngine._process_window",
        "dynamo_tpu.engine.jax_engine:JaxEngine._emit",
    ],
    "per_token": [
        "dynamo_tpu.llm.backend:Backend.generate",
        "dynamo_tpu.llm.processor:Processor._chat",
        "dynamo_tpu.llm.processor:Processor._completion",
        "dynamo_tpu.llm.kv_router.scheduler:KvScheduler.schedule",
    ],
    "frame_name_segments": ["step"],
}

# Derived from the registry grammar; byte-identical to the legacy
# analyzer.HOT_RE for ["step"]. Engine functions matching this are hot
# frames by name (DL005 origins AND dynahot scheduler-kind roots).
HOT_FRAME_RE = re.compile(
    "(^|_)(?:" + "|".join(re.escape(s)
                          for s in HOT_ROOTS["frame_name_segments"])
    + ")($|_)")

# hot-by-name roots only apply under these path markers (mirrors the
# legacy DL005 scoping: engine modules)
HOT_NAME_PATH_MARKERS = ("engine/",)

# hot-region propagation: loop depth saturates here (recursion guard —
# depth 3+ already means "at least thousands of iterations per step")
DEPTH_CAP = 8

# DL022: array-materialization callables whose constant-arg form inside
# a loop rebuilds the same device constant every iteration
_CONST_ARRAY_CALLS = frozenset({
    "jnp.asarray", "jnp.array", "np.asarray", "np.array",
    "numpy.asarray", "numpy.array", "jax.numpy.asarray",
    "jax.numpy.array",
})

# DL022: always-invariant compile-style constructors
_COMPILE_CALLS = frozenset({"re.compile", "struct.Struct"})

# DL023: receivers that make an Attribute call a logging/trace call
_LOG_RECV_RE = re.compile(r"(?i)(^|\.)(log|logger|logging|trace|tracer)$")
# DL023: level/guard spellings accepted in an enclosing `if` (superset of
# DL018's SAMPLE_GUARD_RE via _is_sample_guard, plus level checks)
_LEVEL_GUARD_RE = re.compile(r"(?i)(level|debug|verbose|trace)")

# DL024: in-place growth / shrink method names on self.<attr> receivers
_GROW_ATTRS = frozenset({"append", "appendleft", "add", "extend",
                         "setdefault"})
_SHRINK_ATTRS = frozenset({"pop", "popitem", "popleft", "remove",
                           "discard", "clear", "move_to_end"})

_BOUNDED_BY_RE = re.compile(r"#\s*bounded-by:\s*(\S.*)")

_DL022_TAGS = frozenset({"DL022", "hot-loop-invariant-work", "all"})
_DL023_TAGS = frozenset({"DL023", "hot-eager-format", "all"})
_DL024_TAGS = frozenset({"DL024", "unbounded-growth", "all"})


@dataclass
class HotFrame:
    """One function in the hot region."""

    key: str          # '<module>:<qualname>'
    kind: str         # 'scheduler' | 'per_token'
    depth: int        # accumulated loop depth from the root (0 = root
    #                   body straight-line; each enclosing hot loop +1)
    root: str         # the root key this frame was reached from


# ------------------------------------------------------- region computation

class _InitTyper(ast.NodeVisitor):
    """Collects ``self.<attr> = <Ctor>(...)`` from one class body."""

    def __init__(self) -> None:
        self.types: Dict[str, str] = {}   # attr -> raw ctor dotted name

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            raw = dotted(node.value.func)
            if raw is not None:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self.types[t.attr] = raw
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if isinstance(node.value, ast.Call) and \
                isinstance(node.target, ast.Attribute) and \
                isinstance(node.target.value, ast.Name) and \
                node.target.value.id == "self":
            raw = dotted(node.value.func)
            if raw is not None:
                self.types[node.target.attr] = raw
        self.generic_visit(node)


def _attr_types(sources: Sequence[ModuleSource], graph: CallGraph
                ) -> Dict[Tuple[str, str, str], str]:
    """(module, class, attr) -> resolved class key 'mod.Class' for
    constructor-typed instance attributes (``__init__`` assignments)."""
    from .callgraph import module_name
    out: Dict[Tuple[str, str, str], str] = {}
    for ms in sources:
        mod = graph.modules.get(module_name(ms.path))
        if mod is None:
            continue
        for node in ast.walk(ms.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == "__init__":
                    typer = _InitTyper()
                    typer.visit(sub)
                    for attr, raw in typer.types.items():
                        m, c = graph._resolve_class(mod, raw)
                        if m is not None:
                            out[(mod.name, node.name, attr)] = \
                                f"{m.name}.{c}"
    return out


def hot_regions(graph: CallGraph,
                sources: Optional[Sequence[ModuleSource]] = None
                ) -> Dict[str, HotFrame]:
    """Hot frames by callgraph reachability from HOT_ROOTS, with
    accumulated per-frame loop depth. Deterministic: sorted worklist,
    monotone depth updates capped at DEPTH_CAP."""
    attr_types = (_attr_types(sources, graph) if sources is not None
                  else {})
    frames: Dict[str, HotFrame] = {}
    roots: List[Tuple[str, str]] = []
    for kind in ("scheduler", "per_token"):
        for key in HOT_ROOTS[kind]:
            if key in graph.functions:
                roots.append((key, kind))
    # legacy name-grammar roots: engine functions with a hot name segment
    for key, fi in sorted(graph.functions.items()):
        norm = fi.path.replace("\\", "/")
        if any(m in norm for m in HOT_NAME_PATH_MARKERS) \
                and HOT_FRAME_RE.search(fi.name):
            roots.append((key, "scheduler"))
    for key, kind in sorted(roots):
        cur = frames.get(key)
        if cur is None or (kind == "per_token"
                           and cur.kind == "scheduler"):
            frames[key] = HotFrame(key, kind, 0, key)

    def _resolve_self_attr(fi, raw: str) -> Optional[str]:
        parts = raw.split(".")
        if len(parts) != 3 or parts[0] not in ("self", "cls"):
            return None
        cls_name = fi.qualname.split(".")[0]
        cls_key = attr_types.get((fi.module, cls_name, parts[1]))
        if cls_key is None:
            return None
        tmod, tcls = cls_key.rsplit(".", 1)
        m = graph.modules.get(tmod)
        if m is None:
            return None
        return graph._resolve_method(m, tcls, parts[2])

    changed = True
    while changed:
        changed = False
        for key in sorted(frames):
            hf = frames[key]
            fi = graph.functions.get(key)
            if fi is None:
                continue
            for cs in fi.calls:
                target = cs.target or _resolve_self_attr(fi, cs.raw)
                if target is None or target not in graph.functions:
                    continue
                depth = min(hf.depth + cs.loop_depth, DEPTH_CAP)
                cur = frames.get(target)
                if cur is None or depth > cur.depth or \
                        (hf.kind == "per_token"
                         and cur.kind == "scheduler"
                         and depth >= cur.depth):
                    frames[target] = HotFrame(target, hf.kind, depth,
                                              hf.root)
                    changed = True
    return frames


# ------------------------------------------------------------ DL022/DL023

def _chain_text(node: ast.AST) -> Optional[str]:
    """Dotted text of a pure Name/Attribute chain, else None."""
    return dotted(node)


def _chain_dots(text: str) -> int:
    return text.count(".")


def _is_empty_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)) and not node.elts:
        return True
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("list", "tuple", "dict", "set",
                                 "frozenset") and not node.args:
        return True
    return False


def _assigned_names(node: ast.AST) -> Set[str]:
    """Names bound anywhere inside ``node`` (loop targets, assigns,
    with-as, comprehension targets)."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                    (ast.Store, ast.Del)):
            out.add(sub.id)
    return out


def _eager_format_arg(node: ast.AST) -> Optional[str]:
    """Display string when ``node`` is an eagerly-formatted value."""
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) and \
            isinstance(node.left, (ast.Constant, ast.JoinedStr)):
        return "%-format"
    if isinstance(node, ast.Call):
        if call_attr(node) == "format":
            return "str.format"
        if isinstance(node.func, ast.Name) and \
                node.func.id in ("str", "repr") and node.args and \
                not isinstance(node.args[0], ast.Constant):
            return f"{node.func.id}() of a structure"
    return None


def _is_level_guard(test: ast.AST) -> bool:
    if _is_sample_guard(test):
        return True
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call) and \
                call_attr(sub) == "isEnabledFor":
            return True
        if isinstance(sub, ast.Name) and _LEVEL_GUARD_RE.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and \
                _LEVEL_GUARD_RE.search(sub.attr):
            return True
    return False


class _FrameChecker(ast.NodeVisitor):
    """DL022/DL023 over ONE hot frame's body (nested defs excluded —
    they are their own frames when the region reaches them)."""

    def __init__(self, ms: ModuleSource, frame: HotFrame, qualname: str,
                 func_node: ast.AST, out: List[Violation]):
        self.ms = ms
        self.frame = frame
        self.qualname = qualname
        self.func_node = func_node
        self.out = out
        self._loops: List[ast.AST] = []
        self._guards = 0
        # full-frame repeated-chain census: text -> [nodes]
        self._chains: Dict[str, List[ast.AST]] = {}
        # names bound by any loop/comprehension in the frame: chains on
        # these bases are per-element reads, not invariant resolution
        self._iter_names: Set[str] = set()

    # -- plumbing ---------------------------------------------------------

    def _suppressed(self, line: int, tags: frozenset) -> bool:
        for probe in (line, line - 1):
            have = self.ms.suppressed.get(probe)
            if have and have & tags:
                return True
        return False

    def _emit(self, node: ast.AST, code: str, tags: frozenset,
              detail: str) -> None:
        if self._suppressed(node.lineno, tags):
            return
        name, summary = RULES[code]
        self.out.append(Violation(
            self.ms.path, node.lineno, getattr(node, "col_offset", 0),
            code, name, f"{summary}: {detail}", self.qualname))

    def _in_loop(self) -> bool:
        return bool(self._loops) or self.frame.depth >= 1

    def _loop_assigned(self) -> Set[str]:
        return _assigned_names(self._loops[-1]) if self._loops else set()

    # -- scoping ----------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is not self.func_node:
            return  # nested def: its own frame if hot
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _visit_loop(self, node) -> None:
        self._loops.append(node)
        self._iter_names |= _assigned_names(node)
        self.generic_visit(node)
        self._loops.pop()

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop
    visit_ListComp = _visit_loop
    visit_SetComp = _visit_loop
    visit_DictComp = _visit_loop
    visit_GeneratorExp = _visit_loop

    def visit_If(self, node: ast.If) -> None:
        guarded = _is_level_guard(node.test)
        self.visit(node.test)
        if guarded:
            self._guards += 1
        for stmt in node.body:
            self.visit(stmt)
        if guarded:
            self._guards -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    # -- DL022 patterns ---------------------------------------------------

    def visit_BoolOp(self, node: ast.BoolOp) -> None:
        # `<invariant chain> or []`: rebuilds the default and re-resolves
        # the chain once per iteration — cache it on the object instead
        if isinstance(node.op, ast.Or) and self._in_loop() and \
                len(node.values) == 2 and \
                _is_empty_literal(node.values[1]):
            text = _chain_text(node.values[0])
            if text and _chain_dots(text) >= 2 and \
                    text.split(".")[0] not in self._loop_assigned():
                self._emit(node, "DL022", _DL022_TAGS,
                           f"`{text} or {ast.unparse(node.values[1])}` "
                           f"re-evaluated every iteration — hoist or "
                           f"cache the invariant default")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        in_local_loop = bool(self._loops)
        if in_local_loop and d in _COMPILE_CALLS:
            self._emit(node, "DL022", _DL022_TAGS,
                       f"`{d}(...)` inside a hot loop — compile once at "
                       f"module scope")
        if in_local_loop and d in _CONST_ARRAY_CALLS and node.args and \
                all(isinstance(a, ast.Constant) for a in node.args):
            self._emit(node, "DL022", _DL022_TAGS,
                       f"`{d}` of constants inside a hot loop "
                       f"materializes the same array every iteration")
        if in_local_loop and isinstance(node.func, ast.Name) and \
                node.func.id == "sorted" and len(node.args) == 1 and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id not in self._loop_assigned():
            self._emit(node, "DL022", _DL022_TAGS,
                       f"`sorted({node.args[0].id})` of a loop-invariant "
                       f"value inside a hot loop")
        # DL023: eager formatting into a log/trace call on a hot frame
        if self._guards == 0 and isinstance(node.func, ast.Attribute) \
                and node.func.attr in LOG_METHODS:
            recv = dotted(node.func.value)
            if recv is not None and _LOG_RECV_RE.search(recv):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    what = _eager_format_arg(arg)
                    if what is not None:
                        self._emit(
                            node, "DL023", _DL023_TAGS,
                            f"{what} built eagerly for "
                            f"`{recv}.{node.func.attr}(...)` on a hot "
                            f"frame — use lazy %-args or guard on "
                            f"level/sampling")
                        break
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try) -> None:
        # exception-probe loop discovery: try/except RuntimeError around
        # asyncio.get_running_loop() raises once per call off-loop —
        # per token on the emit path. Cache the loop/thread identity.
        if self._in_loop():
            probes = [sub for stmt in node.body
                      for sub in ast.walk(stmt)
                      if isinstance(sub, ast.Call)
                      and dotted(sub.func) == "asyncio.get_running_loop"]
            catches_rt = any(
                h.type is not None and isinstance(h.type, ast.Name)
                and h.type.id == "RuntimeError" for h in node.handlers)
            if probes and catches_rt:
                self._emit(node, "DL022", _DL022_TAGS,
                           "`asyncio.get_running_loop()` probed under "
                           "`except RuntimeError` per iteration — an "
                           "exception is raised on every off-loop call; "
                           "cache the loop/thread identity once")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # full-frame census of deep invariant chains (resolved at finish)
        if isinstance(node.ctx, ast.Load):
            parent = getattr(node, "_dl_parent", None)
            if not isinstance(parent, ast.Attribute):
                text = _chain_text(node)
                if text and _chain_dots(text) >= 2:
                    self._chains.setdefault(text, []).append(node)
        self.generic_visit(node)

    def finish(self) -> None:
        for text, nodes in sorted(self._chains.items()):
            base = text.split(".")[0]
            if len(nodes) < 3 or base in ("self", "cls") or \
                    base in self._iter_names:
                continue
            node = nodes[2]
            self._emit(node, "DL022", _DL022_TAGS,
                       f"attribute chain `{text}` resolved "
                       f"{len(nodes)}x in one hot frame — bind it to a "
                       f"local once")


# ------------------------------------------------------------------ DL024

class _GrowScan(ast.NodeVisitor):
    """One class body: growth sites, shrink/bound/reset evidence."""

    def __init__(self) -> None:
        self.grows: List[Tuple[str, str, ast.AST]] = []  # (attr, how, node)
        self.evidence: Dict[str, str] = {}  # attr -> why it is bounded
        self._func: List[str] = []

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return node.attr
        return None

    def visit_FunctionDef(self, node) -> None:
        self._func.append(node.name)
        self.generic_visit(node)
        self._func.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _fn(self) -> str:
        return self._func[-1] if self._func else "<class>"

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            attr = self._self_attr(node.func.value)
            if attr is not None:
                if node.func.attr in _GROW_ATTRS:
                    self.grows.append((attr, f".{node.func.attr}()", node))
                elif node.func.attr in _SHRINK_ATTRS:
                    self.evidence.setdefault(
                        attr, f"`.{node.func.attr}()` in `{self._fn()}`")
        # len(self.X) anywhere = a bound is being checked/maintained
        if isinstance(node.func, ast.Name) and node.func.id == "len" \
                and node.args:
            attr = self._self_attr(node.args[0])
            if attr is not None:
                parent = getattr(node, "_dl_parent", None)
                if isinstance(parent, ast.Compare):
                    self.evidence.setdefault(
                        attr, f"`len(self.{attr})` bound check in "
                              f"`{self._fn()}`")
        # deque(maxlen=...) / bounded-ring constructor
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                attr = self._self_attr(el)
                if attr is not None:
                    if self._fn() == "__init__":
                        if self._bounded_ctor(node.value, t, el):
                            self.evidence.setdefault(
                                attr, "`deque(maxlen=...)` ring")
                    else:
                        # reset/swap outside __init__ empties the
                        # collection on some path
                        self.evidence.setdefault(
                            attr, f"reassigned in `{self._fn()}`")
                sub = el if isinstance(el, ast.Subscript) else None
                if sub is not None:
                    a = self._self_attr(sub.value)
                    if a is not None:
                        idx = sub.slice
                        if isinstance(idx, ast.Slice):
                            self.evidence.setdefault(
                                a, f"slice-assign truncation in "
                                   f"`{self._fn()}`")
                        elif isinstance(idx, ast.Tuple) and \
                                any(isinstance(e, ast.Slice)
                                    for e in idx.elts):
                            # ndarray-style `self.buf[:, slots] = ...`:
                            # in-place write into a preallocated region,
                            # not growth
                            pass
                        else:
                            self.grows.append((a, "[k]=", node))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # `self._q: Optional[deque] = deque(maxlen=N) if cap else None`
        attr = self._self_attr(node.target)
        if attr is not None and node.value is not None:
            if self._fn() == "__init__":
                if self._bounded_ctor(node.value, node.target,
                                      node.target):
                    self.evidence.setdefault(
                        attr, "`deque(maxlen=...)` ring")
            else:
                self.evidence.setdefault(
                    attr, f"reassigned in `{self._fn()}`")
        self.generic_visit(node)

    def _bounded_ctor(self, value: ast.AST, target: ast.AST,
                      el: ast.AST) -> bool:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                d = dotted(sub.func)
                tail = d.rsplit(".", 1)[-1] if d else None
                if tail == "deque" and any(kw.arg == "maxlen"
                                           for kw in sub.keywords):
                    return True
        return False

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            attr = self._self_attr(
                t.value if isinstance(t, ast.Subscript) else t)
            if attr is not None:
                self.evidence.setdefault(
                    attr, f"`del` in `{self._fn()}`")
        self.generic_visit(node)


def _class_fields_bounded(cls_node: ast.ClassDef) -> Dict[str, str]:
    """Dataclass-style class-level fields built as bounded rings:
    ``decisions: deque = field(default_factory=lambda: deque(maxlen=N))``."""
    out: Dict[str, str] = {}
    for stmt in cls_node.body:
        target = None
        value = None
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            target, value = stmt.target.id, stmt.value
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            target, value = stmt.targets[0].id, stmt.value
        if target is None or value is None:
            continue
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                d = dotted(sub.func)
                tail = d.rsplit(".", 1)[-1] if d else None
                if tail == "deque" and any(kw.arg == "maxlen"
                                           for kw in sub.keywords):
                    out[target] = "`deque(maxlen=...)` ring"
    return out


def _bounded_by(ms: ModuleSource, line: int) -> Optional[str]:
    lines = ms.src.splitlines()
    for probe in (line, line - 1):
        if 1 <= probe <= len(lines):
            m = _BOUNDED_BY_RE.search(lines[probe - 1])
            if m:
                return m.group(1).strip()
    return None


# ------------------------------------------------------------------ driver

def analyze_hot(sources: Sequence[ModuleSource],
                graph: Optional[CallGraph] = None,
                regions_out: Optional[dict] = None) -> List[Violation]:
    """The dynahot pass: hot regions from HOT_ROOTS + DL022/023/024."""
    if graph is None:
        graph = CallGraph.build(sources)
    frames = hot_regions(graph, sources)
    if regions_out is not None:
        regions_out["frames"] = frames
    out: List[Violation] = []
    by_mod: Dict[str, ModuleSource] = {}
    from .callgraph import module_name
    for ms in sources:
        by_mod[module_name(ms.path)] = ms

    # DL022/DL023: walk each hot frame's def once
    frames_by_mod: Dict[str, Dict[str, HotFrame]] = {}
    for key, hf in frames.items():
        mod, qual = key.split(":", 1)
        frames_by_mod.setdefault(mod, {})[qual] = hf
    for mod_name_, want in sorted(frames_by_mod.items()):
        ms = by_mod.get(mod_name_)
        if ms is None:
            continue
        for qual, func_node in _iter_funcs(ms.tree):
            hf = want.get(qual)
            if hf is None:
                continue
            checker = _FrameChecker(ms, hf, qual, func_node, out)
            checker.visit(func_node)
            checker.finish()

    # DL024: class-wide growth-vs-evidence, growth sites restricted to
    # hot (request-path) frames
    hot_quals: Dict[Tuple[str, str], HotFrame] = {}
    for key, hf in frames.items():
        mod, qual = key.split(":", 1)
        hot_quals[(mod, qual)] = hf
    name24, summary24 = RULES["DL024"]
    attr_types = _attr_types(sources, graph)
    for ms in sources:
        mod_name_ = module_name(ms.path)
        for cls_node in [n for n in ast.walk(ms.tree)
                         if isinstance(n, ast.ClassDef)]:
            scan = _GrowScan()
            for stmt in cls_node.body:
                scan.visit(stmt)
            evidence = dict(_class_fields_bounded(cls_node))
            evidence.update(scan.evidence)
            # qualname prefix for methods of this (top-level) class
            for attr, how, node in scan.grows:
                if attr in evidence:
                    continue
                # `.m()` on a constructor-typed attribute whose class
                # defines `m` is a delegated method call (the callee
                # class gets its own scan), not builtin-collection growth
                if how.startswith("."):
                    meth = how[1:-2]
                    ctor_key = attr_types.get(
                        (mod_name_, cls_node.name, attr))
                    if ctor_key is not None:
                        cmod, ccls = ctor_key.rsplit(".", 1)
                        ci = graph.modules.get(cmod)
                        if ci is not None and ccls in ci.classes and \
                                meth in ci.classes[ccls].methods:
                            continue
                # which function is this site in?
                qual = _enclosing_qual(node)
                if qual is None:
                    continue
                hf = hot_quals.get((mod_name_, qual))
                if hf is None:
                    continue
                if _bounded_by(ms, node.lineno):
                    continue
                suppressed = False
                for probe in (node.lineno, node.lineno - 1):
                    tags = ms.suppressed.get(probe)
                    if tags and tags & _DL024_TAGS:
                        suppressed = True
                if suppressed:
                    continue
                out.append(Violation(
                    ms.path, node.lineno,
                    getattr(node, "col_offset", 0), "DL024", name24,
                    f"{summary24}: `self.{attr}{how}` grows on the "
                    f"request path (hot via {hf.root.split(':', 1)[1]}) "
                    f"with no removal/bound/ring in class "
                    f"`{cls_node.name}` — evict, cap, or justify with "
                    f"`# bounded-by: <reason>`", qual))
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out


def _iter_funcs(tree: ast.AST):
    """Yield (qualname, func_node) for every def, with class/function
    nesting in the qualname (matches callgraph._Collector)."""

    def rec(node: ast.AST, stack: List[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name])
                yield qual, child
                yield from rec(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, stack + [child.name])
            else:
                yield from rec(child, stack)

    yield from rec(tree, [])


def _enclosing_qual(node: ast.AST) -> Optional[str]:
    """Qualname of the function a node sits in (via _dl_parent chain)."""
    parts: List[str] = []
    cur = getattr(node, "_dl_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "_dl_parent", None)
    if not parts:
        return None
    return ".".join(reversed(parts))
