"""dynaflow: whole-program module/import/call-graph builder.

The per-file rules (DL001-DL007) are intra-procedural by construction —
they cannot see an async endpoint calling a sync helper that blocks three
frames down the call stack. This module builds the project-wide view the
interprocedural rules need:

- a **module map** (root-relative path → dotted module name),
- per-module **import alias resolution** (``import x.y as z``,
  ``from ..pkg import name``, re-export chains through ``__init__``),
- a **function table** with async-ness and dotted qualnames (methods are
  attributed to their class; nested defs to their enclosing function),
- **call edges** resolved through aliases, ``self``/``cls`` attribution
  (including single-inheritance base-class lookup), and plain/dotted
  module references, and
- **blocking-call propagation**: which functions transitively reach a
  blocking primitive (``time.sleep``, ``open``, ``requests.*``, ...)
  within a bounded call depth.

Resolution is deliberately conservative: an edge is only recorded when
the callee resolves to a project function. Attribute calls on unknown
objects (``self.engine.foo()``) produce no edge — a whole-program lint
must never guess, or its violations stop being actionable. Calls passed
*as arguments* (``asyncio.to_thread(helper)``) create no edge either:
the helper runs off-loop, which is exactly the sanctioned fix for DL008.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .analyzer import (BLOCKING_BUILTINS, BLOCKING_CALLS, BLOCKING_PREFIXES,
                       ModuleSource, call_attr, dotted, host_sync_what)

# suppression tags that quiet DL008 at a call site or at the blocking sink
_DL008_TAGS = frozenset({"DL008", "transitive-blocking-in-async", "all"})
# ... and DL005 at a host-sync sink (interprocedural hot-path pass)
_DL005_TAGS = frozenset({"DL005", "jax-host-sync-in-hot-path", "all"})

DEFAULT_DL008_DEPTH = 4  # max sync frames between the async def and the sink

# task-spawning wrappers: their first argument is a coroutine CALL whose
# target becomes a concurrency root (dynarace root inference)
SPAWN_TAILS = frozenset({"spawn_tracked", "create_task", "ensure_future"})
# registration calls whose function-reference arguments become handler
# roots: pub/sub subscriptions fire per message
HANDLER_REG_TAILS = frozenset({"subscribe"})
# aiohttp-style route registrations: handler refs next to a "/path" arg
ROUTE_REG_TAILS = frozenset({"get", "post", "put", "delete", "patch",
                             "add_get", "add_post", "add_put", "add_delete",
                             "add_route"})


def module_name(rel_path: str) -> str:
    """'dynamo_tpu/llm/tokenizer.py' -> 'dynamo_tpu.llm.tokenizer';
    package __init__ files map to the package itself."""
    p = rel_path.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


@dataclass
class CallSite:
    line: int
    col: int
    raw: str                      # callee as written ('self.foo', 'mod.fn')
    target: Optional[str] = None  # resolved function key, if any
    # lexical loop nesting of the call site within its own function frame
    # (0 = straight-line code). dynahot multiplies this into hot-region
    # depth: a callee invoked from inside a per-token loop inherits that
    # loop's iteration cost.
    loop_depth: int = 0


@dataclass
class SpawnSite:
    """``spawn_tracked(self._loop(), ...)``-style site: the spawned
    coroutine's target function becomes a concurrency root."""

    line: int
    raw: str                      # spawned callee as written
    in_loop: bool                 # spawned per loop iteration → reentrant
    target: Optional[str] = None


@dataclass
class HandlerRef:
    """A function REFERENCE (not call) registered as a handler —
    ``dcp.subscribe(subject, self._on_events)``, aiohttp route handlers.
    Handlers fire per message/request, so their targets are reentrant
    concurrency roots."""

    line: int
    raw: str
    target: Optional[str] = None


@dataclass
class FuncInfo:
    key: str          # '<module>:<qualname>'
    module: str
    qualname: str     # 'Class.method' / 'func' / 'func.inner'
    name: str
    is_async: bool
    lineno: int
    path: str
    is_async_gen: bool = False    # async def containing yield
    calls: List[CallSite] = field(default_factory=list)
    # direct blocking primitives: (line, what) — suppressed ones excluded
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    # direct host-sync primitives (DL005 sinks) — suppressed ones excluded
    host_sync: List[Tuple[int, str]] = field(default_factory=list)
    spawns: List[SpawnSite] = field(default_factory=list)
    handler_refs: List[HandlerRef] = field(default_factory=list)


@dataclass
class ClassInfo:
    module: str
    name: str                      # top-level class name
    bases: List[str] = field(default_factory=list)  # raw dotted base exprs
    methods: Set[str] = field(default_factory=set)


@dataclass
class ModuleGraph:
    name: str
    path: str
    is_package: bool = False      # __init__.py (relative imports anchor here)
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> dotted
    functions: Dict[str, FuncInfo] = field(default_factory=dict)  # qualname
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    suppressed: Dict[int, Set[str]] = field(default_factory=dict)


@dataclass
class BlockPath:
    """Nearest blocking primitive reachable from a (sync) function."""

    depth: int              # 0 = the function itself blocks
    chain: List[str]        # function keys, this function -> ... -> sink fn
    sink_path: str
    sink_line: int
    what: str


def _is_offload_call(call: ast.Call) -> bool:
    """Calls whose function-object arguments run OFF the event loop."""
    d = dotted(call.func)
    if d in ("asyncio.to_thread",):
        return True
    return isinstance(call.func, ast.Attribute) and \
        call.func.attr in ("run_in_executor", "to_thread")


class _Collector(ast.NodeVisitor):
    """One pass over a module: imports, classes, functions, call sites,
    direct blocking primitives. Calls are attributed to the *innermost*
    enclosing function; module-level calls run at import time and are
    not an event-loop hazard, so they are dropped."""

    def __init__(self, mod: ModuleGraph):
        self.mod = mod
        self._classes: List[str] = []
        self._funcs: List[FuncInfo] = []
        self._loops: List[int] = [0]  # per-function loop depth

    # ------------------------------------------------------------- imports

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.asname:
                self.mod.imports[alias.asname] = alias.name
            else:
                root = alias.name.split(".")[0]
                self.mod.imports[root] = root

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            # relative import: level 1 anchors at this module's package
            # (the module itself when it IS a package __init__)
            pkg = self.mod.name.split(".")
            up = len(pkg) - node.level + (1 if self.mod.is_package else 0)
            if up < 0:
                up = 0
            base_parts = pkg[:up] + ([node.module] if node.module else [])
            base = ".".join(p for p in base_parts if p)
        for alias in node.names:
            if alias.name == "*":
                continue
            target = f"{base}.{alias.name}" if base else alias.name
            self.mod.imports[alias.asname or alias.name] = target

    # ------------------------------------------------------------- scoping

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if not self._classes and not self._funcs:  # top-level classes only
            ci = ClassInfo(self.mod.name, node.name,
                           bases=[dotted(b) for b in node.bases
                                  if dotted(b)])
            self.mod.classes[node.name] = ci
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()

    def _visit_func(self, node, is_async: bool) -> None:
        qual = ".".join(self._classes
                        + [f.name for f in self._funcs] + [node.name])
        fi = FuncInfo(key=f"{self.mod.name}:{qual}", module=self.mod.name,
                      qualname=qual, name=node.name, is_async=is_async,
                      lineno=node.lineno, path=self.mod.path)
        self.mod.functions[qual] = fi
        if len(self._classes) == 1 and not self._funcs and \
                self._classes[0] in self.mod.classes:
            self.mod.classes[self._classes[0]].methods.add(node.name)
        self._funcs.append(fi)
        self._loops.append(0)
        self.generic_visit(node)
        self._loops.pop()
        self._funcs.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node, False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node, True)

    def _visit_loop(self, node) -> None:
        self._loops[-1] += 1
        self.generic_visit(node)
        self._loops[-1] -= 1

    visit_While = _visit_loop
    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def _mark_async_gen(self, node) -> None:
        if self._funcs and self._funcs[-1].is_async:
            self._funcs[-1].is_async_gen = True
        self.generic_visit(node)

    visit_Yield = _mark_async_gen
    visit_YieldFrom = _mark_async_gen

    # --------------------------------------------------------------- calls

    def _suppressed(self, line: int,
                    tags: frozenset = _DL008_TAGS) -> bool:
        for probe in (line, line - 1):
            have = self.mod.suppressed.get(probe)
            if have and have & tags:
                return True
        return False

    def _note_spawns_and_handlers(self, node: ast.Call, d: Optional[str],
                                  fn: FuncInfo) -> None:
        tail = (d.rsplit(".", 1)[-1] if d is not None
                else call_attr(node))
        if tail in SPAWN_TAILS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Call):
                raw = dotted(arg.func)
                if raw is not None:
                    fn.spawns.append(SpawnSite(node.lineno, raw,
                                               self._loops[-1] > 0))
            return
        if tail in HANDLER_REG_TAILS:
            for arg in node.args:
                raw = dotted(arg)
                if raw is not None and not isinstance(arg, ast.Name):
                    fn.handler_refs.append(HandlerRef(node.lineno, raw))
                elif isinstance(arg, ast.Name):
                    fn.handler_refs.append(HandlerRef(node.lineno, arg.id))
            return
        if tail in ROUTE_REG_TAILS:
            # only when some string arg looks like a URL path — this is
            # what keeps dict.get("key", fallback) out of the root set
            if any(isinstance(a, ast.Constant) and isinstance(a.value, str)
                   and a.value.startswith("/") for a in node.args):
                for arg in node.args:
                    raw = dotted(arg)
                    if raw is not None:
                        fn.handler_refs.append(HandlerRef(node.lineno, raw))

    def visit_Call(self, node: ast.Call) -> None:
        if self._funcs:
            fn = self._funcs[-1]
            d = dotted(node.func)
            if d is not None:
                fn.calls.append(CallSite(node.lineno, node.col_offset, d,
                                         loop_depth=self._loops[-1]))
            what = None
            if d is not None and (d in BLOCKING_CALLS
                                  or d in BLOCKING_BUILTINS
                                  or any(d.startswith(p)
                                         for p in BLOCKING_PREFIXES)):
                what = d
            if what is not None and not self._suppressed(node.lineno):
                fn.blocking.append((node.lineno, what))
            sync = host_sync_what(node, d, call_attr(node))
            if sync is not None and \
                    not self._suppressed(node.lineno, _DL005_TAGS):
                fn.host_sync.append((node.lineno, sync))
            self._note_spawns_and_handlers(node, d, fn)
        if _is_offload_call(node):
            # visit only the callee expr: function-object args escape to a
            # thread, so neither their edges nor their blocking count here
            self.visit(node.func)
            return
        self.generic_visit(node)


class CallGraph:
    """The resolved whole-program graph."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleGraph] = {}
        self.functions: Dict[str, FuncInfo] = {}  # key -> FuncInfo

    # ------------------------------------------------------------ building

    @classmethod
    def build(cls, sources: Sequence[ModuleSource]) -> "CallGraph":
        g = cls()
        for ms in sources:
            is_pkg = ms.path.replace(os.sep, "/").endswith("/__init__.py")
            mod = ModuleGraph(name=module_name(ms.path), path=ms.path,
                              is_package=is_pkg, suppressed=ms.suppressed)
            g.modules[mod.name] = mod
            _Collector(mod).visit(ms.tree)
        for mod in g.modules.values():
            for fi in mod.functions.values():
                g.functions[fi.key] = fi
        for mod in g.modules.values():
            for fi in mod.functions.values():
                first = fi.qualname.split(".")[0]
                cls_name = first if first in mod.classes else None
                for cs in fi.calls:
                    cs.target = g._resolve(mod, cs.raw, cls_name, fi)
                for sp in fi.spawns:
                    sp.target = g._resolve(mod, sp.raw, cls_name, fi)
                for hr in fi.handler_refs:
                    hr.target = g._resolve(mod, hr.raw, cls_name, fi)
        return g

    # ---------------------------------------------------------- resolution

    def _resolve(self, mod: ModuleGraph, raw: str,
                 cls_name: Optional[str], fi: FuncInfo,
                 _depth: int = 0) -> Optional[str]:
        if _depth > 8:
            return None
        parts = raw.split(".")
        if parts[0] in ("self", "cls") and cls_name is not None \
                and len(parts) == 2:
            return self._resolve_method(mod, cls_name, parts[1])
        if len(parts) == 1:
            name = parts[0]
            # sibling/child nested def inside the same enclosing FUNCTION
            # (a bare name never resolves to a method of the own class)
            parent = fi.qualname.rsplit(".", 1)[0] \
                if "." in fi.qualname else None
            for scope in (fi.qualname, parent):
                if scope and scope in mod.functions \
                        and f"{scope}.{name}" in mod.functions:
                    return f"{mod.name}:{scope}.{name}"
            if name in mod.functions:
                return f"{mod.name}:{name}"
            if name in mod.classes:
                return self._resolve_method(mod, name, "__init__")
            if name in mod.imports:
                return self._resolve_dotted(mod.imports[name], _depth + 1)
            return None
        head, rest = parts[0], parts[1:]
        if head in mod.imports:
            return self._resolve_dotted(
                mod.imports[head] + "." + ".".join(rest), _depth + 1)
        if head in mod.classes and len(rest) == 1:
            return self._resolve_method(mod, head, rest[0])
        return self._resolve_dotted(raw, _depth + 1)

    def _resolve_method(self, mod: ModuleGraph, cls_name: str,
                        meth: str, _seen: Optional[Set[str]] = None
                        ) -> Optional[str]:
        """Method lookup with base-class walking (project classes only)."""
        _seen = _seen or set()
        key = f"{mod.name}.{cls_name}"
        if key in _seen:
            return None
        _seen.add(key)
        qual = f"{cls_name}.{meth}"
        if qual in mod.functions:
            return f"{mod.name}:{qual}"
        ci = mod.classes.get(cls_name)
        if ci is None:
            return None
        for base_raw in ci.bases:
            base_mod, base_cls = self._resolve_class(mod, base_raw)
            if base_mod is not None:
                hit = self._resolve_method(base_mod, base_cls, meth, _seen)
                if hit is not None:
                    return hit
        return None

    def _resolve_class(self, mod: ModuleGraph, raw: str
                       ) -> Tuple[Optional[ModuleGraph], Optional[str]]:
        parts = raw.split(".")
        if len(parts) == 1:
            if parts[0] in mod.classes:
                return mod, parts[0]
            if parts[0] in mod.imports:
                return self._find_class(mod.imports[parts[0]])
            return None, None
        if parts[0] in mod.imports:
            return self._find_class(
                mod.imports[parts[0]] + "." + ".".join(parts[1:]))
        return self._find_class(raw)

    def _find_class(self, dotted_name: str, _depth: int = 0
                    ) -> Tuple[Optional[ModuleGraph], Optional[str]]:
        if _depth > 8:
            return None, None
        for cut in range(len(dotted_name.split(".")) - 1, 0, -1):
            parts = dotted_name.split(".")
            mname, rest = ".".join(parts[:cut]), parts[cut:]
            m = self.modules.get(mname)
            if m is None:
                continue
            if len(rest) == 1:
                if rest[0] in m.classes:
                    return m, rest[0]
                if rest[0] in m.imports:  # re-export (__init__ chains)
                    return self._find_class(m.imports[rest[0]], _depth + 1)
            return None, None
        return None, None

    def _resolve_dotted(self, dotted_name: str,
                        _depth: int = 0) -> Optional[str]:
        """Longest-module-prefix lookup; follows __init__ re-exports."""
        if _depth > 8:
            return None
        parts = dotted_name.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mname = ".".join(parts[:cut])
            m = self.modules.get(mname)
            if m is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                name = rest[0]
                if name in m.functions:
                    return f"{mname}:{name}"
                if name in m.classes:
                    return self._resolve_method(m, name, "__init__")
                if name in m.imports:
                    return self._resolve_dotted(m.imports[name], _depth + 1)
                return None
            if len(rest) == 2:
                qual = ".".join(rest)
                if qual in m.functions:
                    return f"{mname}:{qual}"
                if rest[0] in m.imports:
                    return self._resolve_dotted(
                        m.imports[rest[0]] + "." + rest[1], _depth + 1)
                return None
            return None
        return None

    # -------------------------------------------- blocking reachability

    def blocking_reachability(self, max_depth: int = DEFAULT_DL008_DEPTH
                              ) -> Dict[str, BlockPath]:
        """For every SYNC project function, the nearest reachable blocking
        primitive within ``max_depth`` sync frames (0 = blocks directly).
        Async callees terminate propagation: their bodies are analyzed as
        their own roots."""
        info: Dict[str, BlockPath] = {}
        for fi in self.functions.values():
            if fi.is_async or not fi.blocking:
                continue
            line, what = fi.blocking[0]
            info[fi.key] = BlockPath(0, [fi.key], fi.path, line, what)
        changed = True
        while changed:
            changed = False
            for fi in self.functions.values():
                if fi.is_async:
                    continue
                for cs in fi.calls:
                    sub = info.get(cs.target) if cs.target else None
                    if sub is None:
                        continue
                    callee = self.functions.get(cs.target)
                    if callee is None or callee.is_async:
                        continue
                    depth = sub.depth + 1
                    cur = info.get(fi.key)
                    if depth <= max_depth and \
                            (cur is None or depth < cur.depth):
                        info[fi.key] = BlockPath(
                            depth, [fi.key] + sub.chain,
                            sub.sink_path, sub.sink_line, sub.what)
                        changed = True
        return info

    # ------------------------------------------------------------- export

    def to_dot(self, reach: Optional[Dict[str, BlockPath]] = None,
               race=None, hot: Optional[dict] = None) -> str:
        """Graphviz export of the project-resolved graph: async defs are
        filled blue, functions that (transitively) reach a blocking
        primitive get a red outline, direct blockers a bold red outline.
        With a dynarace ``RaceModel``, concurrency roots get a bold
        orange outline and shared-state-touching functions a double
        border (peripheries=2). With a dynahot region map (key ->
        HotFrame), hot frames are shaded amber — deeper accumulated
        loop depth shades darker — and the label carries
        ``hot d=<depth>``."""
        reach = reach if reach is not None else self.blocking_reachability()
        # amber ramp by loop depth: straight-line hot body -> deep loops
        hot_ramp = ("#fff4cc", "#ffe08a", "#ffc44d", "#ff9e2c")
        lines = ["digraph dynaflow {",
                 '  rankdir=LR; node [shape=box, fontsize=10];']
        for key, fi in sorted(self.functions.items()):
            attrs = []
            hf = hot.get(key) if hot is not None else None
            if fi.is_async:
                attrs.append('style=filled, fillcolor="#cfe8ff"')
            elif hf is not None:
                shade = hot_ramp[min(hf.depth, len(hot_ramp) - 1)]
                attrs.append(f'style=filled, fillcolor="{shade}"')
            bp = reach.get(key)
            if bp is not None:
                attrs.append('color=red' + (', penwidth=2'
                                            if bp.depth == 0 else ''))
            if race is not None:
                if key in race.roots:
                    attrs.append('color="#e06c00", penwidth=2.5')
                if key in race.shared_funcs:
                    attrs.append('peripheries=2')
            label = key.replace(":", "\\n")
            if hf is not None:
                label += f"\\nhot d={hf.depth}"
            lines.append(f'  "{key}" [label="{label}"'
                         + (", " + ", ".join(attrs) if attrs else "") + "];")
        seen = set()
        for fi in self.functions.values():
            for cs in fi.calls:
                if cs.target and cs.target in self.functions:
                    edge = (fi.key, cs.target)
                    if edge not in seen:
                        seen.add(edge)
                        lines.append(f'  "{fi.key}" -> "{cs.target}";')
        lines.append("}")
        return "\n".join(lines) + "\n"
