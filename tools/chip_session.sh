#!/bin/bash
# One-shot TPU measurement session: runs every queued hardware measurement
# in VERDICT-priority order, each time-boxed, so a mid-session relay wedge
# loses the tail instead of everything. Results land in bench_results/
# (one JSON file per step — the last line of each bench run) plus a full
# transcript per step.
#
# Usage:  bash tools/chip_session.sh [outdir]        (defaults bench_results)
# Env:    PYTHONPATH must include /root/.axon_site; JAX_PLATFORMS=axon.
#
# Priority order (VERDICT r4 "Next round"):
#   1. headline     — the driver-verified number everything flows through
#   2. int8 A/B     — same 1b workload, weight-only int8 (r4 task 2)
#   3. 8b headline  — north-star model size, int8 (r4 task 3)
#   4. prefill A/B  — flash prefill kernel ±DYN_PREFILL_PALLAS
#   5. sweep        — batch geometry roofline
#   6. multiturn    — host-tier TTFT with the overlapped restores
#   7. disagg       — on-chip A/B with transfer breakdown

set -u
cd "$(dirname "$0")/.."
OUT=${1:-bench_results}
mkdir -p "$OUT"
export PYTHONPATH=${PYTHONPATH:-/root/repo:/root/.axon_site}
export JAX_PLATFORMS=${JAX_PLATFORMS:-axon}

run_step() {  # name timeout_s args...
    local name=$1 tmo=$2; shift 2
    echo "=== [$name] python bench.py $* (timeout ${tmo}s) ==="
    timeout "$tmo" python bench.py "$@" \
        > "$OUT/$name.stdout" 2> "$OUT/$name.stderr"
    local rc=$?
    tail -1 "$OUT/$name.stdout" > "$OUT/$name.json" 2>/dev/null
    echo "[$name] rc=$rc  $(cat "$OUT/$name.json" 2>/dev/null | head -c 300)"
    # keep going regardless: later steps still matter after one failure
    return 0
}

# 1. headline (driver workload, defaults)
run_step headline 1200

# 2. int8 weight-only A/B on the same workload (decode is HBM-bound:
#    expect tok/s up from halved weight bytes/step)
run_step int8_1b 1200 --dtype int8

# 3. 8B north-star (BASELINE.md model size; int8 is what fits 16 GB)
run_step headline_8b 2400 --model 8b --dtype int8 --concurrency 16

# 4. flash prefill kernel A/B (same workload, kernel prefill on)
DYN_PREFILL_PALLAS=1 run_step prefill_pallas 1200

# 5. batch-geometry sweep (each distinct max_batch:K pays one warmup)
run_step sweep 4200 --sweep \
    "32:64:4,32:64:16,64:64:8,64:64:16,128:64:16,64:128:8,128:128:8,128:128:16"

# 6. multiturn host-tier TTFT: no-tier baseline, then the tier
run_step multiturn_base 1500 --scenario multiturn --host-pages 0
run_step multiturn_tier 2400 --scenario multiturn --host-pages 4096
# int8-compressed tier: halves the relay bytes per page move — the lever
# aimed at the r1 "restores cost more than recompute" finding
run_step multiturn_tier_int8 2400 --scenario multiturn --host-pages 4096 \
    --host-tier-int8

# 7. disagg A/B with the transfer breakdown
run_step disagg 2400 --scenario disagg

# 8. disagg with int8-compressed KV transfer: halves transfer_mb /
#    ingest time in the breakdown fields (lossy, opt-in)
DYN_KV_TRANSFER_INT8=1 run_step disagg_int8 2400 --scenario disagg

# 9. dynashard sharded serving A/B (ISSUE 12 / ROADMAP item 3): one
#    unsharded engine vs data-parallel mesh-sharded replicas behind the
#    real HTTP + KV-router stack at identical workload. On a single
#    chip this degrades to wiring validation; on a multi-chip slice the
#    tok/s ratio is the headline. Compile counts must stay 0 per
#    replica (the under-sharding fence contract).
run_step sharded_tp2 2400 --scenario sharded --mesh model=2 --dp-replicas 2
# 10. the 8B north-star across a model=2 submesh: int8 8B ≈ 12.8 GB of
#     16 GB HBM on one chip — model-parallel removes the squeeze
run_step sharded_8b 3600 --scenario sharded --model 8b --dtype int8 \
    --mesh model=2 --dp-replicas 1 --concurrency 16

# 11. dynaturbo decode hot-path A/B (ISSUE 16): identical decode-heavy
#     workload, legacy arm (all hot-path optimizations off) first, then
#     the overhauled path; each record carries itl_raw_chunk_p99_ms +
#     the per-bucket cost table + loop-lag p99 + the compile fence.
run_step hotpath_legacy 1800 --scenario hotpath --prof-sample 2 \
    --hotpath-legacy --report-out "$OUT/hotpath_legacy_full.json"
run_step hotpath 1800 --scenario hotpath --prof-sample 2 \
    --report-out "$OUT/hotpath_full.json"
# the quoted evidence table (docs/hot_path.md format)
python -m tools.cost_diff "$OUT/hotpath_legacy_full.json" \
    "$OUT/hotpath_full.json" > "$OUT/hotpath_cost_diff.txt" 2>&1 || true

# 12. dynaheat cache A/B (ISSUE 17): the shared-prefix workload under
#     HBM pool pressure with an int8 host tier, four arms per run
#     (lru/serial control, cost-evict, overlap-restore, cost+overlap) —
#     realized hit rate + TTFT p95 + restore_wait + evict fate split per
#     arm, compile fence 0 everywhere. The fp16-tier run isolates what
#     int8 page moves buy on the relay.
run_step cache_ab 3600 --scenario shared --cache-ab --host-pages 4096 \
    --report-out "$OUT/cache_ab_full.json"
run_step cache_ab_fp16 3600 --scenario shared --cache-ab \
    --host-pages 4096 --host-tier-fp16 \
    --report-out "$OUT/cache_ab_fp16_full.json"

# 13. dynahot measured-fixes re-quote (ISSUE 18): the hotpath and
#     shared scenarios after the DL022 hot-loop-invariant fixes
#     (cached Sequence stop sets, thread-id emit routing, hoisted
#     router overlap). Chip numbers supersede the CPU cost_diff quoted
#     in docs/static_analysis.md; compile fence must stay 0 and greedy
#     token identity is pinned by tests/test_hotpath.py.
run_step dynahot_hotpath 1800 --scenario hotpath --prof-sample 2 \
    --report-out "$OUT/dynahot_hotpath_full.json"
run_step dynahot_shared 2400 --scenario shared \
    --report-out "$OUT/dynahot_shared_full.json"
# diff against the step-11 optimized arm: isolates what the dynahot
# fixes add on top of the dynaturbo overhaul
python -m tools.cost_diff "$OUT/hotpath_full.json" \
    "$OUT/dynahot_hotpath_full.json" > "$OUT/dynahot_cost_diff.txt" 2>&1 || true

# 14. dynablack armed-vs-off A/B + mid-bench capture (ISSUE 19): the
#     hotpath workload with the flight recorder armed (default window)
#     must match the disarmed arm within noise — the zero-measured-cost
#     acceptance bar — and the armed run trips a manual capture whose
#     bundle is archived next to the BENCH report and rendered to a
#     postmortem transcript as the renderer-never-errors proof.
DYN_BLACKBOX_WINDOW_S=0 run_step blackbox_off 1800 --scenario hotpath \
    --prof-sample 2 --report-out "$OUT/blackbox_off_full.json"
run_step blackbox_armed 1800 --scenario hotpath --prof-sample 2 \
    --trip-incident --report-out "$OUT/blackbox_armed_full.json"
python -m tools.cost_diff "$OUT/blackbox_off_full.json" \
    "$OUT/blackbox_armed_full.json" > "$OUT/blackbox_cost_diff.txt" 2>&1 || true
python -m dynamo_tpu.admin.incident \
    "$OUT/blackbox_armed_full.incident.json" \
    > "$OUT/blackbox_postmortem.txt" 2>&1 || true

# 15. dynaform measured-fix re-quote (ISSUE 20): hotpath and shared
#     after the DL026 warmup-form-drift fix (warmup pre-compiles the
#     logprobs_topn decode-window variants, EngineConfig.warmup_logprobs).
#     Chip numbers supersede the CPU cost_diff quoted in
#     docs/static_analysis.md; the compile fence must stay 0 on both —
#     on chips a missed form would cost whole seconds per bucket, which
#     is exactly what the warmed variants buy. Diff against the step-13
#     dynahot arm to isolate what the dynaform fix adds on top.
run_step dynaform_hotpath 1800 --scenario hotpath --prof-sample 2 \
    --report-out "$OUT/dynaform_hotpath_full.json"
run_step dynaform_shared 2400 --scenario shared \
    --report-out "$OUT/dynaform_shared_full.json"
python -m tools.cost_diff "$OUT/dynahot_hotpath_full.json" \
    "$OUT/dynaform_hotpath_full.json" > "$OUT/dynaform_cost_diff.txt" 2>&1 || true
python -m tools.cost_diff "$OUT/dynahot_shared_full.json" \
    "$OUT/dynaform_shared_full.json" \
    >> "$OUT/dynaform_cost_diff.txt" 2>&1 || true

echo "=== chip session complete; results in $OUT/ ==="
grep -h . "$OUT"/*.json 2>/dev/null | head -20
