"""cost_diff: before/after diff of two ``--prof-sample`` BENCH reports.

The hot-path evidence format (docs/hot_path.md): every decode hot-path
change quotes a per-bucket ``dispatch_us`` / ``device_us`` delta from the
profiler cost table, plus the headline client-visible metrics riding the
same record. This tool turns two ``bench.py --report-out`` JSON files
into that quote::

    python -m tools.cost_diff before.json after.json

Accepts either a full BENCH-shaped record (``detail.bucket_cost``) or a
bare ``{"bucket_cost": {...}}`` / ``{bucket: {...}}`` mapping, so it also
diffs the ``bench_results/*.json`` files chip_session.sh leaves behind.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

# headline scalars quoted alongside the table when both reports carry them
HEADLINE_KEYS = (
    "itl_raw_chunk_p99_ms",
    "itl_p99_ms",
    "ttft_p50_ms",
    "ttft_p95_ms",
    "loop_lag_p99_ms",
    "output_tok_per_s",
    "post_warmup_compiles",
)

# dynaheat cache counter family (bench.py --scenario shared flat keys):
# realized hit rates, the allocation prefix split, restore-pipeline cost,
# and the eviction fate split — so a cache A/B quote is one command over
# the two arms' --report-out files
CACHE_KEYS = (
    "prefix_hit_rate",
    "hit_rate_windowed",
    "device_hit_blocks",
    "host_restored_blocks",
    "fresh_blocks",
    "restore_wait_ms",
    "restore_batch_pages_mean",
    "evict_offloaded_total",
    "evict_dropped_total",
    "host_evictions_total",
)


def _bucket_cost(report: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    detail = report.get("detail")
    if isinstance(detail, dict) and isinstance(detail.get("bucket_cost"),
                                               dict):
        return detail["bucket_cost"]
    if isinstance(report.get("bucket_cost"), dict):
        return report["bucket_cost"]
    # bare mapping: every value already looks like a bucket row
    if report and all(isinstance(v, dict) and ("dispatch_us" in v
                                               or "device_us" in v)
                      for v in report.values()):
        return report
    return {}


def _detail(report: Dict[str, Any]) -> Dict[str, Any]:
    d = report.get("detail")
    return d if isinstance(d, dict) else report


def diff_reports(before: Dict[str, Any],
                 after: Dict[str, Any]) -> Dict[str, Any]:
    """Structured diff: per-bucket dispatch/device deltas + headline
    scalars. Buckets present on only one side keep ``None`` for the
    missing side (bucket shapes can legitimately change across an
    overhaul — e.g. longer decode windows rename ``decode_window:BxKxP``
    keys)."""
    b_cost, a_cost = _bucket_cost(before), _bucket_cost(after)
    buckets: List[Dict[str, Any]] = []
    for key in sorted(set(b_cost) | set(a_cost)):
        b, a = b_cost.get(key), a_cost.get(key)
        row: Dict[str, Any] = {"bucket": key}
        for col in ("dispatch_us", "device_us"):
            bv = None if b is None else b.get(col)
            av = None if a is None else a.get(col)
            row[f"{col}_before"] = bv
            row[f"{col}_after"] = av
            row[f"{col}_delta"] = (av - bv if bv is not None
                                   and av is not None else None)
        row["samples_before"] = None if b is None else b.get("samples")
        row["samples_after"] = None if a is None else a.get("samples")
        buckets.append(row)
    b_det, a_det = _detail(before), _detail(after)

    def _scalar_family(keys) -> Dict[str, Dict[str, Optional[float]]]:
        fam: Dict[str, Dict[str, Optional[float]]] = {}
        for key in keys:
            bv, av = b_det.get(key), a_det.get(key)
            if bv is None and av is None:
                continue
            fam[key] = {
                "before": bv, "after": av,
                "delta": (av - bv if isinstance(bv, (int, float))
                          and isinstance(av, (int, float)) else None),
            }
        return fam

    return {"buckets": buckets,
            "headline": _scalar_family(HEADLINE_KEYS),
            "cache": _scalar_family(CACHE_KEYS)}


def _fmt(v: Optional[float], unit: str = "") -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        # sub-1 magnitudes are rates/ratios — one decimal would erase
        # the whole signal (0.2433 → "0.2")
        return (f"{v:.3f}{unit}" if abs(v) < 1 else f"{v:.1f}{unit}")
    return f"{v}{unit}"


def format_table(diff: Dict[str, Any]) -> str:
    lines = []
    if diff["buckets"]:
        head = (f"{'bucket':<28} {'dispatch_us':>24} {'Δdisp':>9} "
                f"{'device_us':>22} {'Δdev':>9} {'samples':>9}")
        lines.append(head)
        lines.append("-" * len(head))
    for row in diff["buckets"]:
        disp = (f"{_fmt(row['dispatch_us_before']):>11} →"
                f"{_fmt(row['dispatch_us_after']):>11}")
        dev = (f"{_fmt(row['device_us_before']):>10} →"
               f"{_fmt(row['device_us_after']):>10}")
        samp = (f"{_fmt(row['samples_before'])}/"
                f"{_fmt(row['samples_after'])}")
        lines.append(f"{row['bucket']:<28} {disp:>24} "
                     f"{_fmt(row['dispatch_us_delta']):>9} {dev:>22} "
                     f"{_fmt(row['device_us_delta']):>9} {samp:>9}")
    if diff["headline"]:
        lines.append("")
        for key, h in diff["headline"].items():
            lines.append(f"{key:<24} {_fmt(h['before'])} → "
                         f"{_fmt(h['after'])}"
                         + (f"  (Δ {_fmt(h['delta'])})"
                            if h["delta"] is not None else ""))
    if diff.get("cache"):
        lines.append("")
        lines.append("cache (dynaheat)")
        lines.append("-" * 16)
        for key, h in diff["cache"].items():
            lines.append(f"{key:<24} {_fmt(h['before'])} → "
                         f"{_fmt(h['after'])}"
                         + (f"  (Δ {_fmt(h['delta'])})"
                            if h["delta"] is not None else ""))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if len(argv) != 2:
        print("usage: python -m tools.cost_diff [--json] "
              "before.json after.json", file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        before = json.load(f)
    with open(argv[1]) as f:
        after = json.load(f)
    diff = diff_reports(before, after)
    if not diff["buckets"] and not diff["cache"] and not diff["headline"]:
        print("no bucket cost table, headline, or cache counters in "
              "either report (run bench.py with --prof-sample N, or "
              "--scenario shared for the cache family)", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(diff, indent=2))
    else:
        print(format_table(diff))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
