#!/usr/bin/env bash
# Pre-commit gate: fast incremental lint over the files this commit
# touches. Wire it up with
#
#     ln -sf ../../tools/precommit.sh .git/hooks/pre-commit
#
# Two layers, both scoped to `git diff --name-only HEAD`:
#   1. ruff (style/pyflakes), if installed — seconds, changed files only
#   2. dynalint --changed — per-file rules on the diffed files; the
#      whole-program passes (dynaflow/dynarace/dynajit/dynaproto/
#      dynahot/dynaform) still analyze the full tree off one shared
#      parse, because a callgraph built from a diff misses the
#      cross-file edges that make them sound (dynaform in particular
#      matches serving call forms in one file against warmup() sites
#      in another).
set -euo pipefail

ROOT="$(git rev-parse --show-toplevel)"
cd "$ROOT"

mapfile -t CHANGED_PY < <(git diff --name-only HEAD -- '*.py' |
                          while read -r f; do [ -f "$f" ] && echo "$f"; done)

if [ "${#CHANGED_PY[@]}" -eq 0 ]; then
    echo "precommit: no changed .py files; skipping lint"
    exit 0
fi

if command -v ruff >/dev/null 2>&1; then
    echo "precommit: ruff over ${#CHANGED_PY[@]} changed file(s)"
    ruff check "${CHANGED_PY[@]}"
else
    echo "precommit: ruff not installed; skipping style layer"
fi

echo "precommit: dynalint --changed"
python -m tools.dynalint --changed

# 3. Prometheus exposition hygiene (ISSUE 19 satellite): both metric
#    planes (frontend Metrics + fleet aggregator) must render exposition
#    with consistent HELP/TYPE per family and well-formed dyn_* names —
#    a malformed scrape silently drops the whole plane in most
#    collectors, which is exactly the blind spot dynablack exists to
#    close. Seconds on CPU; runs on every commit.
echo "precommit: prometheus exposition hygiene"
JAX_PLATFORMS=cpu python -m pytest -q tests/test_blackbox.py \
    -k exposition -p no:cacheprovider
