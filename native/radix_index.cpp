// Native radix/prefix index over chained KV block hashes.
//
// The C++ hot path for the KV-aware router (reference
// lib/llm/src/kv_router/indexer.rs — Rust RadixTree with per-worker
// hash→node lookup; SURVEY §7 hard part (d): "making the radix
// indexer/scheduler fast in Python — port to C++ extension if needed").
// Semantics mirror dynamo_tpu/llm/kv_router/indexer.py exactly; the
// Python KvIndexer picks this backend via ctypes when the shared library
// builds (dynamo_tpu/utils/native.py).
//
// Thread model: single writer (the router's event loop), matching the
// reference's indexer-confined-to-one-runtime design (indexer.rs:37,499).

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Node {
  uint64_t hash;
  Node *parent;
  std::unordered_map<uint64_t, Node *> children;
  std::unordered_set<uint64_t> workers;

  Node(uint64_t h, Node *p) : hash(h), parent(p) {}
};

struct Index {
  Node root;
  // worker id → (block hash → node): O(1) Removed / worker eviction
  std::unordered_map<uint64_t, std::unordered_map<uint64_t, Node *>> lookup;

  Index() : root(0, nullptr) {}
};

void delete_subtree(Node *n) {
  for (auto &kv : n->children) delete_subtree(kv.second);
  delete n;
}

void maybe_prune(Index *ix, Node *node) {
  while (node != &ix->root && node->workers.empty() &&
         node->children.empty() && node->parent != nullptr) {
    Node *parent = node->parent;
    parent->children.erase(node->hash);
    delete node;
    node = parent;
  }
}

}  // namespace

extern "C" {

void *dyn_radix_create() { return new Index(); }

void dyn_radix_destroy(void *p) {
  Index *ix = static_cast<Index *>(p);
  for (auto &kv : ix->root.children) delete_subtree(kv.second);
  delete ix;
}

void dyn_radix_apply_stored(void *p, uint64_t worker, uint64_t parent_hash,
                            int has_parent, const uint64_t *hashes,
                            size_t n) {
  Index *ix = static_cast<Index *>(p);
  auto &wl = ix->lookup[worker];
  Node *node = &ix->root;
  if (has_parent) {
    auto it = wl.find(parent_hash);
    if (it != wl.end()) node = it->second;
  }
  for (size_t i = 0; i < n; ++i) {
    uint64_t h = hashes[i];
    auto have = wl.find(h);
    if (have != wl.end()) {  // worker already holds this block
      node = have->second;
      continue;
    }
    Node *child;
    auto cit = node->children.find(h);
    if (cit != node->children.end()) {
      child = cit->second;
    } else {
      child = new Node(h, node);
      node->children.emplace(h, child);
    }
    child->workers.insert(worker);
    wl.emplace(h, child);
    node = child;
  }
}

void dyn_radix_apply_removed(void *p, uint64_t worker, const uint64_t *hashes,
                             size_t n) {
  Index *ix = static_cast<Index *>(p);
  auto lit = ix->lookup.find(worker);
  if (lit == ix->lookup.end()) return;
  auto &wl = lit->second;
  for (size_t i = 0; i < n; ++i) {
    auto it = wl.find(hashes[i]);
    if (it == wl.end()) continue;
    Node *node = it->second;
    wl.erase(it);
    node->workers.erase(worker);
    maybe_prune(ix, node);
  }
}

void dyn_radix_remove_worker(void *p, uint64_t worker) {
  Index *ix = static_cast<Index *>(p);
  auto lit = ix->lookup.find(worker);
  if (lit == ix->lookup.end()) return;
  for (auto &kv : lit->second) {
    kv.second->workers.erase(worker);
    maybe_prune(ix, kv.second);
  }
  ix->lookup.erase(lit);
}

// Walk the chain from the root accumulating per-worker contiguous match
// counts. Writes up to `cap` (worker, score) pairs; returns the number
// written (reference indexer.rs find_matches → OverlapScores).
size_t dyn_radix_find_matches(void *p, const uint64_t *hashes, size_t n,
                              uint64_t *out_workers, uint32_t *out_scores,
                              size_t cap) {
  Index *ix = static_cast<Index *>(p);
  std::unordered_map<uint64_t, uint32_t> scores;
  Node *node = &ix->root;
  for (size_t i = 0; i < n; ++i) {
    auto it = node->children.find(hashes[i]);
    if (it == node->children.end()) break;
    node = it->second;
    for (uint64_t w : node->workers) ++scores[w];
  }
  size_t out = 0;
  for (auto &kv : scores) {
    if (out >= cap) break;
    out_workers[out] = kv.first;
    out_scores[out] = kv.second;
    ++out;
  }
  return out;
}

size_t dyn_radix_block_count(void *p) {
  Index *ix = static_cast<Index *>(p);
  size_t n = 0;
  std::vector<Node *> stack{&ix->root};
  while (!stack.empty()) {
    Node *cur = stack.back();
    stack.pop_back();
    n += cur->children.size();
    for (auto &kv : cur->children) stack.push_back(kv.second);
  }
  return n;
}

}  // extern "C"
