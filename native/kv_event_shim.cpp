// C ABI KV-event shim (reference lib/bindings/c/src/lib.rs:52-297:
// dynamo_llm_init / dynamo_kv_event_publish_stored / _removed — a C ABI
// loaded by engine processes to publish KV cache events without linking
// the runtime).
//
// TPU re-design: external native engines call the same C ABI; events land
// in an in-process ring buffer, and the host bridge
// (dynamo_tpu/llm/kv_router/publisher.py NativeEventBridge) drains it via
// ctypes and forwards onto the distributed event bus. This keeps the ABI
// engine-facing (no network client in the shim) while the bus stays the
// single event plane.
//
// Wire layout per event (little-endian, matching the Python side's
// struct parsing):
//   u8  kind        (1 = stored, 2 = removed)
//   u64 event_id
//   u64 parent_hash (stored only; ~0 = none)
//   u32 num_blocks
//   u64 block_hash * num_blocks

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct ShimState {
  std::string ns, component;
  int64_t worker_id = 0;
  uint32_t kv_block_size = 0;
  bool initialized = false;
  std::vector<uint8_t> buf;
  uint64_t dropped = 0;  // whole events discarded at the high-water mark
  std::mutex mu;
};

ShimState g_state;

// If no bridge is draining, the buffer must not grow without bound: above
// the high-water mark the OLDEST whole events are discarded (the router
// treats a lossy stream as stale-but-safe — a dropped "stored" only costs
// a routing hit, a dropped "removed" is corrected at the next miss).
// Dropping cuts down to the LOW-water mark so a saturated publisher pays
// one front-erase memmove per ~2 MiB of events, not per event.
constexpr uintptr_t kBufHighWater = 4ULL << 20;  // 4 MiB
constexpr uintptr_t kBufLowWater = 2ULL << 20;   // 2 MiB

// Size of the record starting at `off`, or 0 if truncated/corrupt.
uintptr_t record_size(const std::vector<uint8_t> &buf, uintptr_t off) {
  if (off + 21 > buf.size()) return 0;  // fixed header = 21 bytes
  uint32_t nb;
  std::memcpy(&nb, buf.data() + off + 17, 4);
  uintptr_t rec = 1 + 8 + 8 + 4 + 8ULL * nb;
  return off + rec <= buf.size() ? rec : 0;
}

// Caller holds g_state.mu.
void enforce_high_water() {
  if (g_state.buf.size() <= kBufHighWater) return;
  uintptr_t cut = 0;
  while (g_state.buf.size() - cut > kBufLowWater) {
    uintptr_t rec = record_size(g_state.buf, cut);
    if (rec == 0) break;
    cut += rec;
    ++g_state.dropped;
  }
  if (cut > 0)
    g_state.buf.erase(g_state.buf.begin(), g_state.buf.begin() + cut);
}

void append_u8(std::vector<uint8_t> &b, uint8_t v) { b.push_back(v); }
void append_u32(std::vector<uint8_t> &b, uint32_t v) {
  uint8_t tmp[4];
  std::memcpy(tmp, &v, 4);
  b.insert(b.end(), tmp, tmp + 4);
}
void append_u64(std::vector<uint8_t> &b, uint64_t v) {
  uint8_t tmp[8];
  std::memcpy(tmp, &v, 8);
  b.insert(b.end(), tmp, tmp + 8);
}

constexpr uint64_t kNoParent = ~0ULL;

}  // namespace

extern "C" {

// Reference signature: dynamo_llm_init(namespace, component, worker_id,
// kv_block_size) — lib/bindings/c/src/lib.rs:52.
int32_t dynamo_llm_init(const char *ns, const char *component,
                        int64_t worker_id, uint32_t kv_block_size) {
  std::lock_guard<std::mutex> lock(g_state.mu);
  g_state.ns = ns ? ns : "";
  g_state.component = component ? component : "";
  g_state.worker_id = worker_id;
  g_state.kv_block_size = kv_block_size;
  g_state.initialized = true;
  return 0;
}

int32_t dynamo_llm_shutdown() {
  std::lock_guard<std::mutex> lock(g_state.mu);
  g_state.initialized = false;
  g_state.buf.clear();
  return 0;
}

// Reference: dynamo_kv_event_publish_stored(event_id, token_ids,
// num_block_tokens, block_ids, num_blocks, parent_hash, lora_id) —
// lib/bindings/c/src/lib.rs:260. block_ids carry the engine's chained
// block hashes (the identity used across engine/router/event planes).
int32_t dynamo_kv_event_publish_stored(uint64_t event_id,
                                       const uint32_t * /*token_ids*/,
                                       const uintptr_t * /*num_block_tokens*/,
                                       const uint64_t *block_ids,
                                       uintptr_t num_blocks,
                                       const uint64_t *parent_hash,
                                       uint64_t /*lora_id*/) {
  std::lock_guard<std::mutex> lock(g_state.mu);
  if (!g_state.initialized) return -1;
  append_u8(g_state.buf, 1);
  append_u64(g_state.buf, event_id);
  append_u64(g_state.buf, parent_hash ? *parent_hash : kNoParent);
  append_u32(g_state.buf, static_cast<uint32_t>(num_blocks));
  for (uintptr_t i = 0; i < num_blocks; ++i)
    append_u64(g_state.buf, block_ids[i]);
  enforce_high_water();
  return 0;
}

int32_t dynamo_kv_event_publish_removed(uint64_t event_id,
                                        const uint64_t *block_ids,
                                        uintptr_t num_blocks) {
  std::lock_guard<std::mutex> lock(g_state.mu);
  if (!g_state.initialized) return -1;
  append_u8(g_state.buf, 2);
  append_u64(g_state.buf, event_id);
  append_u64(g_state.buf, kNoParent);
  append_u32(g_state.buf, static_cast<uint32_t>(num_blocks));
  for (uintptr_t i = 0; i < num_blocks; ++i)
    append_u64(g_state.buf, block_ids[i]);
  enforce_high_water();
  return 0;
}

// Host-bridge drain: copies up to `cap` bytes of whole events into `out`,
// removes them from the buffer, returns bytes written.
uintptr_t dynamo_kv_events_drain(uint8_t *out, uintptr_t cap) {
  std::lock_guard<std::mutex> lock(g_state.mu);
  uintptr_t n = g_state.buf.size() < cap ? g_state.buf.size() : cap;
  if (n == 0) return 0;
  // only cut on event boundaries: walk records until the next would
  // exceed n
  uintptr_t end = 0;
  while (end < n) {
    uintptr_t rec = record_size(g_state.buf, end);
    if (rec == 0 || end + rec > n) break;
    end += rec;
  }
  std::memcpy(out, g_state.buf.data(), end);
  g_state.buf.erase(g_state.buf.begin(), g_state.buf.begin() + end);
  return end;
}

// Events discarded because nothing drained the shim before the buffer hit
// its high-water mark (observability for the bridge to report).
uint64_t dynamo_kv_events_dropped() {
  std::lock_guard<std::mutex> lock(g_state.mu);
  return g_state.dropped;
}

int64_t dynamo_llm_worker_id() {
  std::lock_guard<std::mutex> lock(g_state.mu);
  return g_state.worker_id;
}

}  // extern "C"
