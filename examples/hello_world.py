"""Three-stage SDK pipeline (reference examples/hello_world/hello_world.py:
Frontend → Middle → Backend with ``depends()`` + streaming endpoints).

Run:  python -m dynamo_tpu.sdk.cli serve examples.hello_world:Frontend
Then: python examples/hello_world.py client   (from the repo root)
"""

from __future__ import annotations

from dynamo_tpu.sdk import async_on_start, depends, dynamo_endpoint, service


@service(dynamo={"namespace": "hello"})
class Backend:
    @dynamo_endpoint()
    async def generate(self, req: str):
        for word in ("hello", "world", req):
            yield f"backend-{word}"


@service(dynamo={"namespace": "hello"})
class Middle:
    backend = depends(Backend)

    @dynamo_endpoint()
    async def generate(self, req: str):
        stream = await self.backend.round_robin(req)
        async for env in stream:
            yield f"middle-{env.data}"


@service(dynamo={"namespace": "hello"})
class Frontend:
    middle = depends(Middle)

    @async_on_start
    async def wait_deps(self):
        await self.middle.wait_for_instances()

    @dynamo_endpoint()
    async def generate(self, req: str):
        stream = await self.middle.round_robin(req)
        async for env in stream:
            yield f"frontend-{env.data}"


async def _client_main():
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.attach()
    client = await drt.namespace("hello").component(
        "Frontend").endpoint("generate").client()
    await client.wait_for_instances()
    stream = await client.round_robin("demo")
    async for env in stream:
        print(env.data)
    await client.close()
    await drt.shutdown()


if __name__ == "__main__":
    import asyncio
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "client":
        asyncio.run(_client_main())
    else:
        print(__doc__)
