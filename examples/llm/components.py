"""The flagship LLM serving components (reference examples/llm/components/:
frontend.py, processor.py, kv_router.py, worker.py, prefill_worker.py —
SURVEY §2.9). Composed into deployment graphs by ``graphs/*.py``.

Service configs (YAML → ServiceConfig) select the model; defaults are the
CI-testable tiny model + byte tokenizer, exactly like the reference's
echo-engine trick but with the real JAX engine."""

from __future__ import annotations

import asyncio
import dataclasses
import logging

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.openai import (ChatCompletionRequest,
                                             CompletionRequest)
from dynamo_tpu.sdk import async_on_start, depends, dynamo_endpoint, service

log = logging.getLogger("examples.llm")

NAMESPACE = "dynamo"
WORKER_COMPONENT = "TpuWorker"


def _build_engine(cfg: dict):
    """JaxEngine + ModelDeploymentCard from a service config dict."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    model = cfg.get("model", "tiny")
    # dtype: int8 → weight-only quantized serving (models/quant.py);
    # checkpoints quantize on the host at load, random-init engines via
    # the engine's quant path — same contract as `run.py --dtype int8`
    quant = "int8" if cfg.get("dtype") == "int8" else None
    params = None
    if model == "tiny":
        mc = ModelConfig.tiny()
        ecfg = EngineConfig(page_size=cfg.get("kv_block_size", 8),
                            num_pages=cfg.get("num_pages", 128),
                            max_batch=8, prefill_chunk=64,
                            prefill_buckets=(64,), batch_buckets=(8,),
                            page_buckets=(16,),
                            host_pages=cfg.get("host_pages", 0),
                            spec_decode=cfg.get("spec_decode", False),
                            spec_tokens=cfg.get("spec_tokens", 4))
        mdc = ModelDeploymentCard(name=cfg.get("served_model_name", "tiny"),
                                  kv_block_size=ecfg.page_size)
    else:
        from dynamo_tpu.models.loader import load_params

        mc = ModelConfig.from_local_path(model)
        ecfg = EngineConfig(page_size=cfg.get("kv_block_size", 64),
                            num_pages=cfg.get("num_pages", 2048),
                            max_batch=cfg.get("max_batch", 32),
                            host_pages=cfg.get("host_pages", 0),
                            spec_decode=cfg.get("spec_decode", False),
                            spec_tokens=cfg.get("spec_tokens", 4))
        mdc = ModelDeploymentCard.from_local_path(
            model, name=cfg.get("served_model_name"))
        mdc.kv_block_size = ecfg.page_size
        try:
            params = load_params(model, mc, quant=quant)
            quant = None  # applied on the host at load
        except FileNotFoundError:
            pass  # config-only dir (tests): random init below
    if cfg.get("host_tier_int8"):
        ecfg = dataclasses.replace(ecfg, host_tier_int8=True)
    engine = JaxEngine(mc, ecfg, seed=cfg.get("seed", 0), params=params,
                       quant=quant)
    if cfg.get("warmup", False):
        engine.warmup()
    return engine, mdc


def _mdc_from_config(cfg: dict) -> ModelDeploymentCard:
    model = cfg.get("model", "tiny")
    if model == "tiny":
        return ModelDeploymentCard(name=cfg.get("served_model_name", "tiny"),
                                   kv_block_size=cfg.get("kv_block_size", 8))
    mdc = ModelDeploymentCard.from_local_path(
        model, name=cfg.get("served_model_name"))
    mdc.kv_block_size = cfg.get("kv_block_size", 64)
    return mdc


# ---------------------------------------------------------------- workers


@service(dynamo={"namespace": NAMESPACE}, resources={"tpu": 1},
         name=WORKER_COMPONENT)
class TpuWorker:
    """Decode(+local prefill) worker (reference components/worker.py:
    engine + KV event/metrics publishing behind a direct()-routable
    token-level endpoint). With ``disagg: true`` the engine is wrapped by
    the conditional-disagg decode plane (remote prefill over the queue +
    KV page transfer)."""

    def __init__(self):
        self.engine, self.mdc = _build_engine(self.service_config)
        self.stats_handler = self.engine.stats
        self.serving_engine = self.engine
        self.publisher = None
        self.disagg = None

    @async_on_start
    async def boot(self):
        from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher

        drt = self.runtime
        await self.mdc.publish(drt.dcp)
        self.publisher = KvEventPublisher(
            drt.dcp, NAMESPACE, WORKER_COMPONENT, drt.instance_id,
            self.engine)
        self.publisher.start()
        if self.service_config.get("disagg"):
            from dynamo_tpu.llm.disagg.decode import build_disagg_decode

            self.disagg = await build_disagg_decode(
                drt, self.engine, namespace=NAMESPACE, model=self.mdc.name)
            self.serving_engine = self.disagg

    @dynamo_endpoint()
    async def generate_tokens(self, request, context):
        from dynamo_tpu.llm.protocols.common import PreprocessedRequest

        pre = PreprocessedRequest.from_dict(request)
        async for out in self.serving_engine.generate(pre, context):
            yield out.to_dict()

    async def on_stop(self):
        if self.publisher:
            await self.publisher.stop()
        await self.engine.stop()


@service(dynamo={"namespace": NAMESPACE}, resources={"tpu": 1})
class PrefillWorker:
    """Dedicated prefill worker (reference components/prefill_worker.py):
    pulls the shared prefill queue, computes prompt KV + first token, and
    pushes KV pages to the requesting decode engine. Elastic: any number
    may pull the same queue."""

    def __init__(self):
        self.engine, self.mdc = _build_engine(self.service_config)
        self.worker = None

    @async_on_start
    async def boot(self):
        from dynamo_tpu.llm.disagg.prefill_worker import PrefillWorker as PW

        self.worker = PW(self.runtime, self.engine, namespace=NAMESPACE)
        self.worker.start()

    @dynamo_endpoint()
    async def mock(self, request, context):
        # health probe (reference prefill_worker.py:139-141 mock endpoint)
        yield {"completed": self.worker.completed if self.worker else 0,
               "failed": self.worker.failed if self.worker else 0}

    async def on_stop(self):
        if self.worker:
            await self.worker.stop()
        await self.engine.stop()


# ----------------------------------------------------------------- router


@service(dynamo={"namespace": NAMESPACE})
class Router:
    """KV-aware router service (reference components/kv_router.py): hosts
    the radix indexer + cost scheduler; ``generate`` maps token_ids →
    (worker_id, overlap_blocks)."""

    def __init__(self):
        self.router = None

    @async_on_start
    async def boot(self):
        from dynamo_tpu.llm.kv_router.router import KvRouter

        cfg = self.service_config
        self.router = KvRouter(
            self.runtime, NAMESPACE, WORKER_COMPONENT,
            block_size=cfg.get("kv_block_size", 8),
            scrape_interval=cfg.get("scrape_interval", 0.5))
        await self.router.start()

    @dynamo_endpoint()
    async def generate(self, request, context):
        token_ids = request["token_ids"]
        worker_id = await self.router.schedule(token_ids)
        yield {"worker_id": worker_id,
               "overlap_blocks": self.router.overlap_for(token_ids,
                                                         worker_id)}

    async def on_stop(self):
        if self.router:
            await self.router.stop()


class _RouterEdge:
    """Adapts the Router service's endpoint to the in-process KvRouter
    interface Processor expects (schedule/overlap_for)."""

    def __init__(self, handle):
        self.handle = handle
        self._last = {}

    async def schedule(self, token_ids, request_id=None):
        # request_id keys the in-process KvRouter's calibration entries;
        # the remote Router service runs its own KvRouter, so the edge
        # just accepts and drops it (no cost block flows back this hop)
        stream = await self.handle.round_robin({"token_ids": list(token_ids)})
        async for env in stream:
            if env.data is not None:
                self._last = env.data
                return self._last["worker_id"]
        raise RuntimeError("router returned no decision")


# -------------------------------------------------------------- processors


class _ProcessorImpl:
    """Shared body for Processor/RoutedProcessor (reference
    components/processor.py: tokenize → route → worker direct() →
    detokenize → OpenAI chunks)."""

    def _setup(self, worker_dep, router):
        from dynamo_tpu.llm.processor import Processor as P

        self.mdc = _mdc_from_config(self.service_config)
        self.impl = P(self.mdc, worker_dep.client, router)

    async def _generate(self, request, context):
        if "messages" in request:
            req = ChatCompletionRequest(**request)
            agen = self.impl.chat(req, context)
        else:
            req = CompletionRequest(**request)
            agen = self.impl.completion(req, context)
        from dynamo_tpu.llm.http.service import _chunk_dict

        async for chunk in agen:
            d = _chunk_dict(chunk)
            if d is not None:
                yield d


@service(dynamo={"namespace": NAMESPACE})
class Processor(_ProcessorImpl):
    """Routerless processor: round-robin over workers (graphs/agg.py)."""

    worker = depends(TpuWorker)

    @async_on_start
    async def boot(self):
        await self.worker.wait_for_instances()
        self._setup(self.worker, router=None)

    @dynamo_endpoint()
    async def generate(self, request, context):
        async for d in self._generate(request, context):
            yield d


@service(dynamo={"namespace": NAMESPACE})
class RoutedProcessor(_ProcessorImpl):
    """KV-routed processor (graphs/agg_router.py): asks the Router for the
    best worker, then direct()-routes the token-level call."""

    worker = depends(TpuWorker)
    router = depends(Router)

    @async_on_start
    async def boot(self):
        await self.worker.wait_for_instances()
        await self.router.wait_for_instances()
        self._setup(self.worker, _RouterEdge(self.router))

    @dynamo_endpoint()
    async def generate(self, request, context):
        async for d in self._generate(request, context):
            yield d


# ---------------------------------------------------------------- frontend


def _make_frontend(processor_service, name):
    """Frontend factory bound to a specific processor implementation
    (reference components/frontend.py spawns the http binary + llmctl
    registration; here the OpenAI HttpService runs in-process and the
    processor is the registered engine)."""

    @service(dynamo={"namespace": NAMESPACE}, name=name)
    class _Frontend:
        processor = depends(processor_service)

        @async_on_start
        async def boot(self):
            from dynamo_tpu.llm.engines import RemoteOpenAIEngine
            from dynamo_tpu.llm.http.service import HttpService, ModelManager

            await self.processor.wait_for_instances()
            cfg = self.service_config
            self.mdc = _mdc_from_config(cfg)
            manager = ModelManager()
            engine = RemoteOpenAIEngine(self.processor.client)
            manager.add_chat_model(self.mdc.name, engine)
            manager.add_completions_model(self.mdc.name, engine)
            self.http = HttpService(manager)
            self.port = cfg.get("port", 8080)
            await self.http.start(cfg.get("host", "0.0.0.0"), self.port)
            log.info("frontend %s on :%d (model %s)", name, self.port,
                     self.mdc.name)

        @dynamo_endpoint()
        async def health(self, request, context):
            yield {"ok": True, "port": self.port}

        async def on_stop(self):
            if getattr(self, "http", None):
                await self.http.stop()

    return _Frontend


Frontend = _make_frontend(Processor, "Frontend")
RoutedFrontend = _make_frontend(RoutedProcessor, "RoutedFrontend")
