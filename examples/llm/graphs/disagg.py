"""Disaggregated graph (reference examples/llm/graphs/disagg.py):
decode workers take requests; long prefills go through the shared queue to
dedicated prefill workers, KV pages stream back over the transfer plane."""

from examples.llm.components import (Frontend, PrefillWorker, Processor,
                                     TpuWorker)

Frontend.link(Processor).link(TpuWorker).link(PrefillWorker)
