"""Disaggregated + KV-routed graph (reference
examples/llm/graphs/disagg_router.py): the full flagship deployment."""

from examples.llm.components import (PrefillWorker, RoutedFrontend,
                                     RoutedProcessor, Router, TpuWorker)

RoutedFrontend.link(RoutedProcessor).link(Router).link(TpuWorker) \
    .link(PrefillWorker)
Frontend = RoutedFrontend
