"""KV-routed aggregated graph (reference examples/llm/graphs/agg_router.py):
Frontend -> Processor -> Router -> TpuWorker with prefix-overlap + load
cost routing."""

from examples.llm.components import (RoutedFrontend, RoutedProcessor, Router,
                                     TpuWorker)

RoutedFrontend.link(RoutedProcessor).link(Router).link(TpuWorker)
Frontend = RoutedFrontend  # serve entry alias
