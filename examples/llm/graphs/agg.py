"""Aggregated serving graph (reference examples/llm/graphs/agg.py):
Frontend -> Processor -> TpuWorker, round-robin routing.

    python -m dynamo_tpu serve examples.llm.graphs.agg:Frontend \
        -f examples/llm/configs/agg.yaml
"""

from examples.llm.components import Frontend, Processor, TpuWorker

Frontend.link(Processor).link(TpuWorker)
